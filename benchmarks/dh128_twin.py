#!/usr/bin/env python3
"""The d_head-128 twin rungs (VERDICT r5 Weak #1 / top_next).

Round 5 explained the weak dense-d512 and long-context MFU rungs with a
*computed* composite ceiling plus a structural d_head-64 argument — the
MXU contracts 128-deep, so 64-deep heads leave half of every attention
contraction's systolic depth idle.  The falsification experiment is the
SAME model FLOPs at MXU-native head depth: d512 at 4 heads × d_head 128
(vs the rung's 8 × 64), and the long-context d256 class at 2 × 128 (vs
4 × 64).  If MFU jumps toward the computed ~44%/~42% ceilings the claim
becomes a measurement; if not, the sink hunt reopens with a named
suspect eliminated.

This harness runs BOTH twins of each pair in ONE process (the repo's
same-window discipline — cross-window wall comparisons are what Weak #3
was about), asserts the pairs are FLOP-identical before timing anything,
and freezes ``DH128_TWIN_r{NN}.json`` with per-row regime labels.  The
MFU claim itself is only settled by the on-chip run: a ``cpu`` regime
row proves the harness and the FLOPs parity, and records the wall ratio
for what a CPU is worth (the artifact says which it was — no CPU row
ever masquerades as chip evidence).

Usage:
  python benchmarks/dh128_twin.py            # VERDICT geometry (on-chip)
  python benchmarks/dh128_twin.py --smoke    # CPU-CI scale, mechanics only
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--smoke", action="store_true",
                   help="CPU-CI scale (mechanics + FLOPs parity; the MFU "
                        "verdict needs the full on-chip run)")
    p.add_argument("--steps", type=int, default=None)
    p.add_argument("--families", default=None,
                   help="comma list of dense,long_context (default both; "
                        "a CPU box can afford the dense pair at true "
                        "geometry but not the 8k-seq long-context pair)")
    try:
        from benchmarks._round import current_round
    except ImportError:
        from _round import current_round

    p.add_argument("--out", default=str(
        REPO / f"DH128_TWIN_r{current_round():02d}.json"))
    args = p.parse_args(argv)

    import importlib.util

    spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    import jax

    from tpudist.utils import transformer_train_flops

    steps = args.steps or (2 if args.smoke else 5)
    if args.smoke:
        # batch 8: divisible by any test-rig data mesh (the conftest's
        # 8 virtual devices included)
        pairs = [
            ("dense", dict(batch=8, seq_len=256, d_model=128, d_ff=512),
             dict(n_heads=4), dict(n_heads=2)),   # dh32 vs dh64 twins
        ]
    else:
        # the VERDICT geometries: identical model FLOPs, head depth is
        # the ONLY thing that moves
        pairs = [
            ("dense", dict(batch=8, seq_len=2048, d_model=512, d_ff=2048),
             dict(n_heads=8), dict(n_heads=4)),   # dh64 vs dh128
            ("long_context",
             dict(batch=4, seq_len=8192, d_model=256, d_ff=1024),
             dict(n_heads=4), dict(n_heads=2)),   # dh64 vs dh128
        ]
    if args.families:
        want = {f.strip() for f in args.families.split(",")}
        pairs = [p_ for p_ in pairs if p_[0] in want]
    regime = jax.devices()[0].device_kind
    rows = {}
    for family, base, shallow, deep in pairs:
        # FLOPs parity is structural (head count cancels out of the
        # matmul accounting) — assert it anyway so a future config edit
        # cannot silently break the twin-ness the comparison rests on
        fl = [transformer_train_flops(
            batch=base["batch"], seq_len=base["seq_len"],
            d_model=base["d_model"], n_layers=4, d_ff=base["d_ff"],
            vocab=256) for _ in (shallow, deep)]
        assert fl[0] == fl[1], "twin rungs must be FLOP-identical"
        for tag, heads in (("base", shallow), ("dh_twin", deep)):
            dh = base["d_model"] // heads["n_heads"]
            row = bench.bench_lm(
                name=f"{family}_{tag}_dh{dh}", n_layers=4,
                precision="bf16", steps=steps, **base, **heads)
            row["regime"] = regime
            row["d_head"] = dh
            rows[f"{family}_{tag}"] = row
            print(json.dumps({f"{family}_{tag}": {
                "d_head": dh, "step_ms": row["step_ms"],
                "mfu_pct_vs_bf16_peak": row["mfu_pct_vs_bf16_peak"]}}),
                flush=True)
        base_row, twin = rows[f"{family}_base"], rows[f"{family}_dh_twin"]
        rows[f"{family}_twin_speedup"] = round(
            base_row["step_ms"] / twin["step_ms"], 4)
    artifact = {
        "regime": regime,
        "smoke": bool(args.smoke),
        "verdict_claim": "d_head-64 leaves the MXU's 128-deep contraction "
                         "half idle; the 128-deep twin at identical model "
                         "FLOPs should recover the computed ceiling",
        "note": ("cpu regime rows validate the harness and the FLOPs "
                 "parity only — the MFU verdict requires the on-chip run"
                 if regime == "cpu" or args.smoke else
                 "on-chip twin measurement"),
        **rows,
    }
    out = Path(args.out)
    tmp = out.with_suffix(".tmp")
    tmp.write_text(json.dumps(artifact, indent=2) + "\n")
    tmp.replace(out)
    print(json.dumps({"wrote": str(out)}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
