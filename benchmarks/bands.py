#!/usr/bin/env python3
"""Multi-run measurement bands for the headline bench rows (VERDICT r4
next #4: single tunnel-noisy runs were being narrated as stable facts).

Methodology, per row family (stated per row in the artifact):

- per-step LM rows: ONE ``bench_lm`` invocation with ``repeats=N`` —
  one compile, N raw timings of the 5-step loop on the same executable,
  so the band is execution/tunnel noise, not compile variance;
- scanned rows: N invocations of ``bench_lm_scanned`` with its default
  min-of-3 statistic — the scan path's published number.  Its band is a
  band of MINIMA and therefore tighter by construction than the raw
  per-step bands; the artifact labels it so the two families are never
  read as the same statistic;
- decode rows: N invocations of ``bench_decode`` (its published
  best-of-3-gens statistic), labeled likewise.

Each invocation APPENDS a session to ``BANDS_r{NN}.json`` (NN = the
round being built, ``benchmarks/_round.py``) and re-pools all sessions
per row (median + [min, max] over every sample) — a later healthy
tunnel window adds evidence instead of overwriting it.

Cross-round carry-forward (VERDICT #8: each round used to restart its
bands from zero samples, so early-round rows were narrated off 3-sample
bands while 9 perfectly valid samples sat in the previous round's
artifact): sessions from the prior round's artifact are imported into
the new round IF their ``code_hash`` — a digest of the measured code
paths (bench.py, models/ops/train/flops) — matches the current tree, so
a kernel or step-function change quietly invalidates old samples
instead of polluting the pool.  Carried sessions keep a ``carried_from``
marker and every pooled row lists per-session provenance, so a reader
can always tell which samples are fresh and which rode in.
"""

from __future__ import annotations

import json
import statistics
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import bench  # noqa: E402


def measurement_code_hash() -> str:
    """Digest of the code that produces band samples: a change anywhere
    in the measured paths (harness, model/kernel code, the train step,
    the FLOPs accounting) invalidates prior-round samples for pooling.
    Deliberately coarse — a one-line comment edit also rotates the hash;
    false invalidation costs a few re-measured samples, false REUSE
    costs a silently wrong band."""
    import hashlib

    h = hashlib.sha256()
    files = [REPO / "bench.py", REPO / "tpudist" / "utils" / "flops.py"]
    for sub in ("models", "ops", "train"):
        files += sorted((REPO / "tpudist" / sub).glob("*.py"))
    for f in files:
        if f.exists():
            h.update(f.name.encode())
            h.update(f.read_bytes())
    return h.hexdigest()[:12]


def carry_forward(artifact: dict, prior_path: Path, code_hash: str) -> dict:
    """Import the prior round's sessions whose ``code_hash`` matches the
    current tree (module doc).  Already-carried sessions keep their
    ORIGINAL provenance marker, so a chain of unchanged rounds stays
    attributed to the round that measured it.  Returns a summary dict
    (stored in the artifact so exclusions are visible, not silent)."""
    info = {"from": prior_path.name, "carried": 0, "excluded_stale": 0}
    try:
        prior = json.loads(prior_path.read_text())
        sessions = prior["sessions"]
    except Exception as e:
        info["error"] = f"unreadable prior artifact: {e!r}"
        return info
    have = {(s.get("carried_from"), s.get("label"))
            for s in artifact["sessions"]}
    for s in sessions:
        if s.get("code_hash") != code_hash:
            # stale code version (or a pre-carry-forward artifact with
            # no hash at all): its samples measured different code
            info["excluded_stale"] += 1
            continue
        origin = s.get("carried_from") or prior_path.name
        if (origin, s.get("label")) in have:
            continue  # re-invocation: already carried
        artifact["sessions"].append({**s, "carried_from": origin})
        info["carried"] += 1
    return info


def _band(values):
    vals = [v for v in values if v is not None]
    if not vals:
        return {"runs": list(values), "median": None, "min": None,
                "max": None}
    return {"runs": list(values), "median": statistics.median(vals),
            "min": min(vals), "max": max(vals)}


def lm_rows(repeats: int, **cfg) -> dict:
    """One compile, ``repeats`` raw timings (bench_lm's repeats param)."""
    import jax

    row = bench.bench_lm(steps=5, repeats=repeats, **cfg)
    c = row["config"]
    peak = row.get("peak_bf16_flops_per_chip")
    n_chips = jax.local_device_count()  # bench_lm's own per-chip divisor
    toks, mfus = [], []
    for ms in row.get("step_ms_runs", [row["step_ms"]]):
        toks.append(round(c["batch"] * c["seq_len"] / (ms / 1e3)
                          / n_chips, 1))
        mfus.append(round(100 * row["model_flops_per_step"]
                          / (ms / 1e3) / (n_chips * peak), 2)
                    if peak else None)
    return {"statistic": "raw 5-step timings, one shared compile",
            "config": c,
            "tokens_per_sec_per_chip_runs": toks,
            "mfu_pct_vs_bf16_peak_runs": mfus}


def pool(sessions) -> dict:
    """Per-row bands over every session's samples."""
    # The decode roofline divides by ONE chip kind's HBM bandwidth; an
    # artifact whose sessions were measured on different kinds has no
    # single valid ceiling — refuse to stamp one rather than quietly
    # using the first session's chip for everyone's samples.
    kinds = sorted({s["device_kind"] for s in sessions
                    if s.get("device_kind")})
    device_kind = kinds[0] if len(kinds) == 1 else None
    merged: dict = {}
    for s in sessions:
        for name, row in s.get("rows", {}).items():
            if "error" in row or "superseded" in row:
                # superseded: the row's measurement CONFIG changed in a
                # later session (e.g. the scanned arm's donate_state
                # fix); raw samples stay in the session record, but the
                # pooled band must not mix configurations.
                continue
            slot = merged.setdefault(
                name, {"statistic": row.get("statistic"),
                       "config": row.get("config"), "samples": {},
                       "provenance": []})
            # per-row provenance: which session contributed, and whether
            # its samples were measured THIS round or carried forward
            prov = {"session": s.get("label"),
                    "carried_from": s.get("carried_from"),
                    "device_kind": s.get("device_kind")}
            if prov not in slot["provenance"]:
                slot["provenance"].append(prov)
            for key, vals in row.items():
                if key.endswith("_runs"):
                    slot["samples"].setdefault(key[:-5], []).extend(vals)
    pooled = {
        name: {"statistic": slot["statistic"], "config": slot["config"],
               "provenance": slot["provenance"],
               **{k: _band(v) for k, v in slot["samples"].items()}}
        for name, slot in merged.items()
    }
    # Decode rows carry a pooled roofline percentage (the ceiling is
    # deterministic per config, so it belongs next to the pooled median,
    # not only inside per-session medians).
    for row in pooled.values():
        cfg = row.get("config") or {}
        band = row.get("tokens_per_sec")
        if not (band and band["median"]
                and {"prompt_len", "max_new"} <= set(cfg)):
            continue  # not a decode row: no roofline field either way
        if len(kinds) > 1:
            row["pct_of_roofline_pooled_median"] = None
            row["roofline_note"] = (
                "sessions span device kinds "
                f"{kinds}: no single HBM ceiling applies to the pooled "
                "median — re-pool per kind for a roofline percentage")
            continue
        from tpudist.utils.flops import HBM_BYTES_PER_S, decode_roofline

        nbytes = 2 if cfg.get("precision") == "bf16" else 4
        roof = decode_roofline(
            batch=cfg["batch"], prompt_len=cfg["prompt_len"],
            max_new=cfg["max_new"], d_model=cfg["d_model"],
            n_layers=cfg["n_layers"], d_ff=cfg["d_ff"],
            vocab=cfg["vocab"], param_bytes=nbytes, cache_bytes=nbytes,
            # the sessions' chip, not the pooling host's (pooling may
            # run on a CPU box over TPU-measured sessions)
            hbm_bytes_per_s=HBM_BYTES_PER_S.get(device_kind))
        if roof:
            row["pct_of_roofline_pooled_median"] = round(
                100 * band["median"]
                / roof["ceiling_tokens_per_sec"], 1)
    return pooled


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--repeats", type=int, default=3)
    try:
        from benchmarks._round import current_round
    except ImportError:
        from _round import current_round

    p.add_argument("--out", default=str(
        REPO / f"BANDS_r{current_round():02d}.json"))
    p.add_argument("--configs", default="dense,long,d1024_b8,d1024_b16,"
                                        "scanned_dense,scanned_d1024,decode,"
                                        "decode_bf16")
    p.add_argument("--session", default=None,
                   help="label for this session (default: seq number)")
    p.add_argument("--carry-from", default="auto",
                   help="prior-round BANDS artifact to import matching-"
                        "code sessions from ('auto': BANDS_r{NN-1}; "
                        "'none': disable)")
    args = p.parse_args(argv)
    want = set(args.configs.split(","))

    out_path = Path(args.out)
    if out_path.exists():
        try:
            artifact = json.loads(out_path.read_text())
            assert "sessions" in artifact
        except Exception:
            # NEVER silently reset accumulated band history: back the
            # unparseable file up and start fresh, loudly.
            backup = out_path.with_suffix(".corrupt")
            out_path.replace(backup)
            print(json.dumps({"warning": f"unparseable {out_path.name} "
                              f"moved to {backup.name}; starting a fresh "
                              "artifact"}), flush=True)
            artifact = {"sessions": [], "pooled": {}}
    else:
        artifact = {"sessions": [], "pooled": {}}

    code_hash = measurement_code_hash()
    artifact["code_hash"] = code_hash
    if args.carry_from != "none":
        prior_path = (REPO / f"BANDS_r{current_round() - 1:02d}.json"
                      if args.carry_from == "auto"
                      else Path(args.carry_from))
        if (prior_path.exists()
                and prior_path.resolve() != out_path.resolve()):
            artifact["carry_forward"] = carry_forward(
                artifact, prior_path, code_hash)
            print(json.dumps({"carry_forward":
                              artifact["carry_forward"]}), flush=True)

    def write_artifact():
        # atomic: a kill mid-write must not truncate the accumulated file
        tmp = out_path.with_suffix(".tmp")
        tmp.write_text(json.dumps(artifact, indent=2) + "\n")
        tmp.replace(out_path)

    import jax

    fresh = [s for s in artifact["sessions"] if not s.get("carried_from")]
    session = {"label": args.session or f"s{len(fresh) + 1}",
               "device_kind": jax.devices()[0].device_kind,
               "repeats": args.repeats, "code_hash": code_hash, "rows": {}}
    artifact["sessions"].append(session)

    def run(name, fn):
        if name not in want:
            return
        t0 = time.perf_counter()
        try:
            session["rows"][name] = fn()
        except Exception as e:  # a wedged section must not void the rest
            session["rows"][name] = {"error": repr(e)}
        session["rows"][name]["wall_s"] = round(time.perf_counter() - t0, 1)
        artifact["pooled"] = pool(artifact["sessions"])
        print(json.dumps({name: session["rows"][name]}), flush=True)
        write_artifact()

    run("dense", lambda: lm_rows(
        args.repeats, name="dense_bf16", batch=8, seq_len=2048, d_model=512,
        n_layers=4, n_heads=8, d_ff=2048, vocab=256, precision="bf16"))
    run("long", lambda: lm_rows(
        args.repeats, name="long_context_bf16", batch=4, seq_len=8192,
        d_model=256, n_layers=4, n_heads=4, d_ff=1024, vocab=256,
        precision="bf16"))
    run("d1024_b8", lambda: lm_rows(
        args.repeats, name="mfu_d1024_bf16", batch=8, seq_len=2048,
        d_model=1024, n_layers=8, n_heads=8, d_ff=4096, vocab=256,
        precision="bf16"))
    run("d1024_b16", lambda: lm_rows(
        args.repeats, name="mfu_d1024_bf16_b16", batch=16, seq_len=2048,
        d_model=1024, n_layers=8, n_heads=8, d_ff=4096, vocab=256,
        precision="bf16"))

    def scanned(name, **cfg):
        rows = [bench.bench_lm_scanned(name=name, skip_plain=True, **cfg)
                for _ in range(args.repeats)]
        return {"statistic": ("min-of-3 per sample (the scan path's "
                              "published statistic) — tighter than the "
                              "raw per-step bands by construction"),
                "config": rows[0]["config"],
                "mfu_pct_vs_bf16_peak_runs":
                    [r["mfu_pct_vs_bf16_peak"] for r in rows]}

    run("scanned_dense", lambda: scanned(
        "dense_bf16_scanned", batch=8, seq_len=2048, d_model=512,
        n_layers=4, n_heads=8, d_ff=2048, vocab=256, scan_k=8))
    run("scanned_d1024", lambda: scanned(
        "mfu_d1024_bf16_b16_scanned", batch=16, seq_len=2048, d_model=1024,
        n_layers=8, n_heads=8, d_ff=4096, vocab=256, scan_k=4))

    def decode(precision="fp32"):
        rows = [bench.bench_decode(precision=precision)
                for _ in range(args.repeats)]
        roof = rows[0].get("roofline")
        vals = [r["value"] for r in rows]
        med = statistics.median(vals)
        return {"statistic": "best-of-3 internal gens per sample "
                             "(bench_decode's published statistic); "
                             "device runs are traced busy-time rates",
                "config": rows[0]["config"],
                "tokens_per_sec_runs": vals,
                "tokens_per_sec_device_runs":
                    [r.get("tokens_per_sec_device") for r in rows],
                "pct_of_roofline_median": round(
                    100 * med / roof["ceiling_tokens_per_sec"], 1)
                if roof else None}

    run("decode", decode)
    run("decode_bf16", lambda: decode(precision="bf16"))
    # re-pool unconditionally: carried-forward sessions must reach the
    # pooled bands even when this invocation ran zero configs
    artifact["pooled"] = pool(artifact["sessions"])
    write_artifact()  # even a zero-row session leaves a valid artifact
    return 0


if __name__ == "__main__":
    sys.exit(main())
