#!/usr/bin/env python3
"""Elastic world-size bench: goodput retained under a mid-run rank kill.

Three tpurun-launched scenarios of the SAME 2-process toy-DP training run
(real cross-process gloo collectives, cadence checkpointing, telemetry):

- ``baseline``       — no fault, the run's clean wall-clock;
- ``fixed_restart``  — ``TPUDIST_FAULT=kill@step:K,rank:1`` with
  ``--max-restarts 1``: the PR-1 path — the whole group restarts at the
  SAME world size and resumes from the last cadence checkpoint (the gap
  lands in the report's ``lost_restart`` component);
- ``elastic_resume`` — the same kill with ``--max-restarts 0 --elastic``:
  the restart budget exhausts immediately and tpurun relaunches at the
  SURVIVING world size (n−1); the worker rebuilds its mesh from the new
  launch contract and resumes through the reshardable-checkpoint path
  (the gap lands in the new ``resize`` component).

Each scenario's row carries the merged goodput report's attribution
(step / ckpt / idle / resize / lost_restart seconds, world sizes by
generation) plus the end-to-end wall-clock and completed iterations from
the worker's own progress stream.  The summary quotes GOODPUT RETAINED —
completed-iterations-per-wall-second relative to the no-fault baseline —
for both recovery paths, and elastic vs fixed head-to-head.  CPU rig
numbers validate the *mechanics* (the recovery paths complete, the
attribution is right, the components sum); wall-clock ratios here are
dominated by XLA compile at these toy scales and are labeled so.

Writes ``BENCH_ELASTIC_r{NN}.json`` (round_snapshot freezes it per
round); stdout carries the rung rows + summary as JSON lines.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import textwrap
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

WORKER = """
import json, os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)  # 1 device per process
os.environ.setdefault("OMP_NUM_THREADS", "1")

import jax
if int(os.environ.get("TPUDIST_NUM_PROCESSES", "1")) > 1:
    # gloo CPU collectives need the distributed client (world > 1 only)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
import optax

from tpudist.checkpoint import CheckpointConfig, CheckpointManager
from tpudist.data import ShardPlan, ShardedLoader, make_toy_data
from tpudist.models import create_toy_model
from tpudist.runtime import bootstrap
from tpudist.runtime.mesh import data_parallel_mesh
from tpudist.train import (TrainLoopConfig, init_model_states,
                           make_multi_model_train_step, run_training)

ctx = bootstrap.initialize()
ITERS = int(os.environ["ELASTIC_ITERS"])
SAVE_EVERY = int(os.environ["ELASTIC_SAVE_EVERY"])

mesh = data_parallel_mesh()
kx, ky = jax.random.split(jax.random.PRNGKey(0))
mx, px = create_toy_model(kx)
my, py = create_toy_model(ky)
models = {"model_X": (mx.apply, px), "model_Y": (my.apply, py)}
tx = optax.adam(1e-3)
states = init_model_states(models, tx)
step = make_multi_model_train_step(
    {k: f for k, (f, _) in models.items()}, tx, mesh)
data = make_toy_data(seed=0)
plan = ShardPlan(num_samples=len(data), num_shards=ctx.num_processes,
                 shard_id=ctx.process_id, seed=0, mode="distributed")
loader = ShardedLoader(data, batch_size=32, plan=plan)

mgr = CheckpointManager(CheckpointConfig(
    directory=os.environ["ELASTIC_CKPT"], save_every=SAVE_EVERY,
    async_save=False))
start = 0
if mgr.latest_step is not None:
    # elastic resume: saved logical shardings re-bind onto THIS mesh
    states, meta = mgr.restore_resharded(states, mesh=mesh)
    start = int(meta["iteration"])

cfg = TrainLoopConfig(total_iterations=ITERS, progress_bar=False,
                      sync_every=4, device_cache=False)
states, losses = run_training(states, step, loader, mesh, config=cfg,
                              ckpt=mgr, start_iteration=start)
mgr.wait_until_finished()
if ctx.process_id == 0:
    with open(os.environ["ELASTIC_OUT"], "a") as f:
        f.write(json.dumps({
            "gen": os.environ.get("TPUDIST_RESTART_COUNT"),
            "world": ctx.num_processes, "start": start, "done": True,
            "latest": mgr.latest_step,
            "loss": float(losses["model_X"])}) + "\\n")
mgr.close()
bootstrap.shutdown()
"""


def run_scenario(name: str, *, iters: int, save_every: int,
                 kill_step: int | None, elastic: bool,
                 max_restarts: int) -> dict:
    """One tpurun-launched run; returns the rung row (merged-report
    attribution + worker progress)."""
    from tpudist.launch.run import main as tpurun_main

    saved_env = dict(os.environ)
    with tempfile.TemporaryDirectory() as td:
        worker = Path(td) / "worker.py"
        worker.write_text(textwrap.dedent(WORKER))
        tele = Path(td) / "tele"
        progress = Path(td) / "progress.jsonl"
        try:
            for var in list(os.environ):
                if var.startswith(("TPUDIST_", "SLURM_", "OMPI_")) or var in (
                        "RANK", "WORLD_SIZE", "MASTER_ADDR", "NODE_RANK"):
                    os.environ.pop(var, None)
            os.environ["ELASTIC_ITERS"] = str(iters)
            os.environ["ELASTIC_SAVE_EVERY"] = str(save_every)
            os.environ["ELASTIC_CKPT"] = str(Path(td) / "ckpt")
            os.environ["ELASTIC_OUT"] = str(progress)
            os.environ["PYTHONPATH"] = (
                str(REPO) + os.pathsep + saved_env["PYTHONPATH"]
                if "PYTHONPATH" in saved_env else str(REPO))
            if kill_step is not None:
                os.environ["TPUDIST_FAULT"] = f"kill@step:{kill_step},rank:1"
            t0 = time.perf_counter()
            rc = tpurun_main(
                ["--nprocs", "2", "--max-restarts", str(max_restarts)]
                + (["--elastic"] if elastic else [])
                + ["--restart-backoff", "0.2",
                   "--tmpdir", str(Path(td) / "scratch"),
                   "--telemetry-dir", str(tele),
                   "--", sys.executable, str(worker)])
            wall = time.perf_counter() - t0
        finally:
            os.environ.clear()
            os.environ.update(saved_env)
        if rc != 0:
            return {"scenario": name, "error": f"tpurun rc={rc}"}
        rows = [json.loads(line) for line in
                progress.read_text().splitlines()] if progress.exists() \
            else []
        dones = [r for r in rows if r.get("done")]
        try:
            report = json.loads((tele / "report.json").read_text())
        except (OSError, ValueError) as e:
            return {"scenario": name, "error": f"no report: {e!r}"}
    g = report["goodput"]
    return {
        "regime": "multiprocess-cpu",
        "scenario": name,
        "iters": iters,
        "completed": dones[-1]["latest"] if dones else None,
        "final_world": dones[-1]["world"] if dones else None,
        "resume_start": dones[-1]["start"] if dones else None,
        "wall_s": round(wall, 2),
        "report_wall_s": report["wall_clock_s"],
        "generations": report["generations"],
        "world_sizes": report.get("world_sizes"),
        "step_s": g["step"]["s"],
        "step_frac": g["step"]["frac"],
        "ckpt_s": g["ckpt"]["s"],
        "resize_s": g["resize"]["s"],
        "lost_restart_s": g["lost_restart"]["s"],
        "goodput_sum_s": report["goodput_sum_s"],
        "iters_per_wall_s": round(iters / wall, 3),
    }


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=24)
    p.add_argument("--save-every", type=int, default=8)
    p.add_argument("--kill-step", type=int, default=13,
                   help="kill rank 1 at this step (after the first "
                        "cadence save, before the second)")
    from benchmarks._round import current_round

    p.add_argument(
        "--out",
        default=str(REPO / f"BENCH_ELASTIC_r{current_round():02d}.json"))
    args = p.parse_args(argv)

    rungs = []
    for name, kill, elastic, restarts in (
            ("baseline", None, False, 0),
            ("fixed_restart", args.kill_step, False, 1),
            ("elastic_resume", args.kill_step, True, 0)):
        r = run_scenario(name, iters=args.iters, save_every=args.save_every,
                         kill_step=kill, elastic=elastic,
                         max_restarts=restarts)
        rungs.append(r)
        print(json.dumps(r), file=sys.stderr, flush=True)

    ok = {r["scenario"]: r for r in rungs if "error" not in r}
    summary = {"summary": "elastic_goodput",
               "interpretation": (
                   "goodput_retained_* = completed-iterations-per-wall-"
                   "second vs the no-fault baseline.  CPU-rig mechanics "
                   "numbers: toy-scale wall clocks are compile-dominated, "
                   "so the honest claims are the ATTRIBUTION ones — the "
                   "elastic run's recovery gap lands in `resize` (not "
                   "lost_restart), the fixed-size run's in "
                   "`lost_restart`, both runs complete their budget, and "
                   "components sum exactly to wall-clock.")}
    base = ok.get("baseline")
    if base:
        for scen in ("fixed_restart", "elastic_resume"):
            if scen in ok:
                summary[f"goodput_retained_{scen}"] = round(
                    ok[scen]["iters_per_wall_s"]
                    / base["iters_per_wall_s"], 3)
    if "fixed_restart" in ok and "elastic_resume" in ok:
        summary["elastic_over_fixed_throughput"] = round(
            ok["elastic_resume"]["iters_per_wall_s"]
            / ok["fixed_restart"]["iters_per_wall_s"], 3)
        summary["elastic_resize_s"] = ok["elastic_resume"]["resize_s"]
        summary["fixed_lost_restart_s"] = \
            ok["fixed_restart"]["lost_restart_s"]
        summary["elastic_completed_at_world"] = \
            ok["elastic_resume"]["final_world"]

    out = {"regime": "multiprocess-cpu", "host_cores": os.cpu_count(),
           "launched_via": "python -m tpudist.launch (tpurun agent), "
                           "2 workers x 1 JAX CPU device, gloo "
                           "cross-process collectives, "
                           "TPUDIST_FAULT kill chaos",
           "rungs": rungs, **summary}
    Path(args.out).write_text(json.dumps(out, indent=2) + "\n")
    for r in rungs:
        print(json.dumps(r), flush=True)
    print(json.dumps(summary), flush=True)
    return 0 if len(ok) == len(rungs) else 1


if __name__ == "__main__":
    sys.exit(main())
