#!/usr/bin/env python3
"""Round-end artifact snapshotter: freeze the DP-scaling and loss-parity
reports into ``SCALING_r{NN}.json`` / ``PARITY_r{NN}.json`` at the repo
root so round-over-round regressions outside the bench.py headline are
visible (each file is the harness's JSON lines verbatim).

- scaling runs on an 8-device virtual CPU mesh in a subprocess (the
  sitecustomize pins the real platform, so the subprocess re-pins to cpu
  via jax.config — the tests/conftest.py trick); rung ratios there validate
  mechanics, not hardware truth, and are labeled ``regime: virtual-cpu``.
- parity also runs on the virtual mesh (demo_model_split needs a 2-wide
  model axis, and the rig exposes one real chip): five entry points, fixed
  seed, final-loss spread — a numerics check, platform-independent.

Usage: python benchmarks/round_snapshot.py [--round N] [--iters 300]
Round defaults to (highest existing BENCH_r*.json round) + 1 — the round
currently being built.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

_VIRTUAL_STUB = """
import os
# BOTH pins are required: jax.config for this process's first backend
# resolution, and the env var for every code path that re-resolves from
# the environment (tpudist initialize() honors an explicit JAX_PLATFORMS;
# without it the axon sitecustomize re-pins the tunnel backend, and a
# wedged tunnel kills the virtual-mesh run — observed r4 loss_parity).
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
import sys
sys.path.insert(0, {repo!r})
sys.argv = ["bench"]
import importlib.util
spec = importlib.util.spec_from_file_location(
    {name!r}, {repo!r} + "/benchmarks/" + {name!r} + ".py")
mod = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mod)
mod.main({argv!r})
"""


def detect_round() -> int:
    try:
        from benchmarks._round import current_round
    except ImportError:
        from _round import current_round

    return current_round()


def _stamp_artifact_header(path: Path, family: str, rnd: int) -> None:
    """Stamp the ``{"artifact": {schema, family, round}}`` header into an
    artifact this snapshot just wrote — declared metadata beats filename
    parsing (``tpudist.plan.artifacts`` validates it against both).
    Idempotent; existing header fields win."""
    try:
        text = path.read_text()
    except OSError:
        return
    header = {"schema": 1, "family": family, "round": rnd}
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        obj = None
    if isinstance(obj, dict):
        declared = obj.get("artifact")
        obj["artifact"] = {**header, **declared} \
            if isinstance(declared, dict) else header
        path.write_text(json.dumps(obj, indent=1) + "\n")
        return
    if isinstance(obj, list):
        return  # plain-array artifacts: the loader wraps them as rows
    # JSONL: prepend one header line unless the first line already is one
    lines = [ln for ln in text.splitlines() if ln.strip()]
    if lines and '"artifact"' in lines[0]:
        return
    path.write_text(json.dumps({"artifact": header}) + "\n" + text)


def run_lines(cmd: list[str], timeout: int,
              env: dict | None = None) -> list[dict]:
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, cwd=REPO, env=env)
    if proc.returncode != 0:
        raise RuntimeError(
            f"{cmd[:2]} failed rc={proc.returncode}:\n{proc.stderr[-2000:]}")
    rows = []
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            rows.append(json.loads(line))
    if not rows:
        raise RuntimeError(f"{cmd[:2]}: no JSON rows in output")
    return rows


def main(argv=None) -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--round", default=None, type=int)
    p.add_argument("--iters", default=300, type=int,
                   help="loss-parity training budget per entry point")
    args = p.parse_args(argv)
    rnd = args.round if args.round is not None else detect_round()

    for label, name, argv in (
        ("SCALING", "scaling", []),
        ("PARITY", "loss_parity", ["--iters", str(args.iters)]),
    ):
        rows = run_lines(
            [sys.executable, "-c",
             _VIRTUAL_STUB.format(repo=str(REPO), name=name, argv=argv)],
            timeout=1800,
        )
        if label == "SCALING":
            # Second regime: TRUE multi-process rungs through the tpurun
            # agent (r4 verdict #3 — the virtual rows alone misread as a
            # scaling collapse).  Detailed artifact:
            # SCALING_MULTIPROC_r{NN}.json; its rung lines merge here.
            # A multiproc failure must not void the completed virtual
            # rows or abort the PARITY pass — record it as a row.
            mp_out = REPO / f"SCALING_MULTIPROC_r{rnd:02d}.json"
            try:
                rows += run_lines(
                    [sys.executable, str(REPO / "benchmarks"
                                         / "scaling_multiproc.py"),
                     "--iters", "32", "--out", str(mp_out)],
                    timeout=900,
                )
            except Exception as e:
                rows.append({"regime": "multiprocess-cpu",
                             "error": repr(e)})
            if mp_out.exists():
                _stamp_artifact_header(mp_out, "SCALING_MULTIPROC", rnd)
        out = REPO / f"{label}_r{rnd:02d}.json"
        out.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        _stamp_artifact_header(out, label, rnd)
        print(f"{out.name}: {json.dumps(rows[-1])}")

    # Serving joins the round scoreboard: serve_bench writes its own
    # artifact (rate rungs + block-size sweep + overhead split); smoke
    # scale here — real numbers come from hardware rounds.  A serving
    # failure must not void the completed SCALING/PARITY snapshots.
    import os

    serve_out = REPO / f"BENCH_SERVE_r{rnd:02d}.json"
    try:
        # --multiproc 2: the tpurun-launched multi-process serve rung
        # (2 disaggregated workers, each SPMD over a 2-device emulated
        # mesh, serialized KV handoff) freezes into the same artifact.
        # --spec: the speculative-decode sweep (draft size x K vs the
        # non-spec device-busy floor) joins the round scoreboard too.
        rows = run_lines(
            [sys.executable, str(REPO / "benchmarks" / "serve_bench.py"),
             "--smoke", "--multiproc", "2", "--devices-per-proc", "2",
             "--spec", "--out", str(serve_out)],
            timeout=900,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        # surface the last MEASUREMENT row, not the trailing
        # {"wrote": ...} status line serve_bench prints after it
        data = [r for r in rows if "wrote" not in r] or rows
        print(f"{serve_out.name}: {json.dumps(data[-1])}")
    except Exception as e:
        serve_out.write_text(json.dumps(
            {"regime": "cpu-smoke", "error": repr(e)}) + "\n")
        print(f"{serve_out.name}: error {e!r}")

    # Elastic world-size rung (PR 12): goodput retained under a mid-run
    # rank kill — elastic-resume vs fixed-size-restart vs no-fault
    # baseline, through real tpurun-launched multi-process runs.
    # Failure-isolated like the serve snapshot.
    elastic_out = REPO / f"BENCH_ELASTIC_r{rnd:02d}.json"
    try:
        rows = run_lines(
            [sys.executable, str(REPO / "benchmarks" / "elastic_bench.py"),
             "--out", str(elastic_out)],
            timeout=900,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        print(f"{elastic_out.name}: {json.dumps(rows[-1])}")
    except Exception as e:
        elastic_out.write_text(json.dumps(
            {"regime": "multiprocess-cpu", "error": repr(e)}) + "\n")
        print(f"{elastic_out.name}: error {e!r}")

    # Observability rung (PR 13): measured metrics+trace overhead twin
    # plus the chaos cross-pool trace acceptance booleans, frozen as
    # BENCH_OBS_r{NN}.json.  Failure-isolated like the serve snapshot.
    obs_out = REPO / f"BENCH_OBS_r{rnd:02d}.json"
    try:
        rows = run_lines(
            [sys.executable, str(REPO / "benchmarks" / "obs_bench.py"),
             # --max-new 48: ≥6 decode blocks per request, so the twin's
             # per-handle TPOT amortizes block-boundary quantization (at
             # the smoke default of 10 a µs-scale host delta can cost a
             # whole extra dispatch block and read as a 2x outlier)
             "--smoke", "--pairs", "7", "--max-new", "48",
             "--out", str(obs_out)],
            timeout=900,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        data = [r for r in rows if "wrote" not in r] or rows
        print(f"{obs_out.name}: {json.dumps(data[-1])}")
    except Exception as e:
        obs_out.write_text(json.dumps(
            {"regime": "cpu-smoke", "error": repr(e)}) + "\n")
        print(f"{obs_out.name}: error {e!r}")

    # Graceful-degradation rung (host-RAM KV tier / preemption / SLO
    # shedding): resume-vs-re-prefill TTFT, protected-tenant attainment
    # under overload, preemption twin — frozen as
    # BENCH_SESSION_r{NN}.json.  Failure-isolated like the serve
    # snapshot.
    session_out = REPO / f"BENCH_SESSION_r{rnd:02d}.json"
    try:
        rows = run_lines(
            [sys.executable, str(REPO / "benchmarks" / "session_bench.py"),
             "--smoke", "--out", str(session_out)],
            timeout=900,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        data = [r for r in rows if "wrote" not in r] or rows
        print(f"{session_out.name}: {json.dumps(data[-1])}")
    except Exception as e:
        session_out.write_text(json.dumps(
            {"regime": "cpu-smoke", "error": repr(e)}) + "\n")
        print(f"{session_out.name}: error {e!r}")

    # Per-tenant adapter rung (paged multi-LoRA pool): adapters-per-
    # batch decode-throughput sweep vs base-only + oracle byte-identity
    # + churn compile pins, frozen as BENCH_ADAPTER_r{NN}.json.
    # Failure-isolated like the serve snapshot.
    adapter_out = REPO / f"BENCH_ADAPTER_r{rnd:02d}.json"
    try:
        rows = run_lines(
            [sys.executable, str(REPO / "benchmarks" / "adapter_bench.py"),
             "--out", str(adapter_out)],
            timeout=900,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        data = [r for r in rows if "wrote" not in r] or rows
        print(f"{adapter_out.name}: {json.dumps(json.loads(data[-1]))}")
    except Exception as e:
        adapter_out.write_text(json.dumps(
            {"regime": "cpu-smoke", "error": repr(e)}) + "\n")
        print(f"{adapter_out.name}: error {e!r}")

    # Fleet-router rung (PR 16): affinity vs round-robin on resume-TTFT
    # and prefix-cache hit rate, plus the replica-kill migration
    # booleans — frozen as BENCH_ROUTER_r{NN}.json.  Failure-isolated
    # like the serve snapshot.
    router_out = REPO / f"BENCH_ROUTER_r{rnd:02d}.json"
    try:
        rows = run_lines(
            [sys.executable, str(REPO / "benchmarks" / "router_bench.py"),
             "--smoke", "--out", str(router_out)],
            timeout=900,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        data = [r for r in rows if "wrote" not in r] or rows
        print(f"{router_out.name}: {json.dumps(data[-1])}")
    except Exception as e:
        router_out.write_text(json.dumps(
            {"regime": "cpu-smoke", "error": repr(e)}) + "\n")
        print(f"{router_out.name}: error {e!r}")

    # Online draft-distillation rung (PR 17): the distribution-shift
    # flywheel — frozen-draft acceptance decay vs gated-hot-swap
    # recovery, swap-latency + gate timelines, byte-identity and
    # compile-pin booleans — frozen as BENCH_DISTILL_r{NN}.json.
    # Failure-isolated like the serve snapshot.
    distill_out = REPO / f"BENCH_DISTILL_r{rnd:02d}.json"
    try:
        rows = run_lines(
            [sys.executable, str(REPO / "benchmarks" / "distill_bench.py"),
             "--smoke", "--out", str(distill_out)],
            timeout=900,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        data = [r for r in rows if "wrote" not in r] or rows
        print(f"{distill_out.name}: {json.dumps(json.loads(data[-1]))}")
    except Exception as e:
        distill_out.write_text(json.dumps(
            {"regime": "cpu-smoke", "error": repr(e)}) + "\n")
        print(f"{distill_out.name}: error {e!r}")

    # Structured-output rung (PR 18): mixed constrained/unconstrained
    # batch — constrained-vs-free per-token overhead, free-lane
    # byte-identity, grammar-churn compile pins — frozen as
    # BENCH_GRAMMAR_r{NN}.json.  Failure-isolated like the serve
    # snapshot.
    grammar_out = REPO / f"BENCH_GRAMMAR_r{rnd:02d}.json"
    try:
        rows = run_lines(
            [sys.executable, str(REPO / "benchmarks" / "grammar_bench.py"),
             "--out", str(grammar_out)],
            timeout=900,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        data = [r for r in rows if "wrote" not in r] or rows
        print(f"{grammar_out.name}: {json.dumps(data[-1])}")
    except Exception as e:
        grammar_out.write_text(json.dumps(
            {"regime": "cpu-smoke", "error": repr(e)}) + "\n")
        print(f"{grammar_out.name}: error {e!r}")

    # Decode per-op attribution (VERDICT Weak #2): trace the bf16 fused
    # decode loop and freeze the table naming the non-matmul residual.
    # Failure-isolated like the serve snapshot.
    prof_out = REPO / f"DECODE_PROFILE_r{rnd:02d}.json"
    try:
        run_lines(
            [sys.executable,
             str(REPO / "benchmarks" / "profile_summary.py"),
             "--capture-decode", "--out", str(prof_out)],
            timeout=600,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        print(f"{prof_out.name}: written")
    except Exception as e:
        prof_out.write_text(json.dumps({"error": repr(e)}) + "\n")
        print(f"{prof_out.name}: error {e!r}")

    # Planner honesty rung (the measurement-driven planner PR): predict
    # every candidate from the round's frozen artifacts, measure them
    # live, freeze the error band the planner quotes on every report.
    # plan_bench writes its own declared header.  Failure-isolated like
    # the serve snapshot.
    plan_out = REPO / f"PLAN_r{rnd:02d}.json"
    try:
        rows = run_lines(
            [sys.executable, str(REPO / "benchmarks" / "plan_bench.py"),
             "--round", str(rnd), "--out", str(plan_out)],
            timeout=1800,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        print(f"{plan_out.name}: {json.dumps(rows[-1])}")
    except Exception as e:
        plan_out.write_text(json.dumps({"error": repr(e)}) + "\n")
        print(f"{plan_out.name}: error {e!r}")

    # Every artifact this snapshot wrote carries the declared header the
    # plan loader validates (declared metadata beats filename parsing);
    # error-path stubs get stamped too, so a failed bench still declares
    # what it was.
    for family, path in (
        ("BENCH_SERVE", serve_out), ("BENCH_ELASTIC", elastic_out),
        ("BENCH_OBS", obs_out), ("BENCH_SESSION", session_out),
        ("BENCH_ADAPTER", adapter_out), ("BENCH_ROUTER", router_out),
        ("BENCH_DISTILL", distill_out), ("BENCH_GRAMMAR", grammar_out),
        ("DECODE_PROFILE", prof_out),
    ):
        if path.exists():
            _stamp_artifact_header(path, family, rnd)


if __name__ == "__main__":
    main()
