#!/usr/bin/env python3
"""Per-tenant adapter bench: the multi-LoRA decode rungs, frozen per
round as ``BENCH_ADAPTER_r{NN}.json``.

One rung family, CPU-safe (tiny model; absolute tok/s is interpreter
mechanics — the RATIOS between arms on one engine are the measurement):

- **adapter_sweep** — the SAME engine, the SAME request schedule (every
  slot decoding a full budget), swept over adapters-per-batch ∈
  {0 (base-only), 1, S/2, S}: each arm loads its adapters, binds them
  round-robin across the slots, decodes to budget, and unloads — so the
  sweep ALSO drives the load/churn path.  Quotes decode throughput per
  arm and the min ratio vs the base-only arm: the claim is that batched
  gathered LoRA decode stays within a stated margin of base decode
  (the delta is two rank-r matmuls per projection against the full
  base matmuls + attention).  The artifact freezes:

  - ``outputs_match`` — every arm's every stream byte-identical to its
    single-adapter sequential ``generate()`` oracle (correctness rides
    along with the measurement);
  - ``ratio_min`` / ``within_margin`` — the throughput acceptance;
  - ``compile_pins_flat`` — jit-cache sizes identical after the whole
    load/bind/unload churn sweep vs after the first arm (zero
    recompilation as tenants churn).

Usage: ``python benchmarks/adapter_bench.py [--smoke] [--out PATH]``
(round_snapshot.py freezes it per round; the tier-1 smoke test asserts
the rung fields).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

CFG = dict(vocab=32, d_model=32, n_layers=2, n_heads=2, d_ff=64,
           max_len=96)

#: throughput acceptance margin: each adapter arm must keep at least
#: this fraction of base-only decode tok/s.  The true cost at rank 8 /
#: d_model 32 is a few percent of FLOPs; 0.4 absorbs CPU-interpreter
#: noise while still catching a pathological (e.g. per-token re-gather
#: or recompile) regression.  The margin actually APPLIED is scaled by
#: a measured noise floor (see ``run_sweep``): on a jittery host two
#: back-to-back runs of the SAME base arm can differ by tens of
#: percent, and a fixed 0.4 then flakes on pure scheduler noise.
MARGIN = 0.4

#: hard floor for the noise-scaled margin: however noisy the host, an
#: adapter arm below 15% of base throughput is a real regression
#: (per-token re-gather or a recompile in the loop), never jitter.
MARGIN_FLOOR = 0.15


def _model(seed: int = 0):
    import jax

    from tpudist.models import create_transformer

    return create_transformer(jax.random.PRNGKey(seed), seq_len=16, **CFG)


_GEN_CACHE: dict = {}


def _oracle(module, params, prompt, max_new, factors, key):
    """Sequential single-adapter reference.  Generators are CACHED per
    adapter (``key``) and rank: ``generate()`` builds a fresh jit per
    call, which across a slots × arms sweep would pay ~16 full scan
    compiles for the same 5 programs — the cache makes the oracle cost
    one compile per (adapter, prompt shape)."""
    import jax.numpy as jnp
    import numpy as np

    from tpudist.models import lora, make_generator

    gen = _GEN_CACHE.get((key, max_new))
    if gen is None:
        col = (lora.adapter_collection(factors, CFG["n_layers"])
               if factors is not None else None)
        mod = module.clone(lora_rank=8) if factors is not None else module
        gen = make_generator(mod, params, max_new, adapters=col)
        _GEN_CACHE[(key, max_new)] = gen
    out = gen(jnp.asarray(prompt)[None])
    return np.asarray(out)[0, len(prompt):].tolist()


def _run_arm(eng, prompts, budgets, adapters_by_slot):
    """Fill every slot, decode everything to budget, return
    ``(streams, decode_wall_s, decode_tokens)`` — wall measured over the
    decode blocks only (admission/prefill excluded: the sweep compares
    DECODE throughput, the hot path the adapter gather sits on)."""
    items = []
    for slot, (p, b, name) in enumerate(
            zip(prompts, budgets, adapters_by_slot)):
        items.append((slot, p, 0.0, slot, b, (), None, name))
    streams = {s: [] for s in range(len(prompts))}
    for slot, tok in eng.start_batch(items).items():
        if tok is not None:
            streams[slot].append(tok)
    while eng.prefilling_slots():
        for slot, tok in eng.advance_prefill().items():
            streams[slot].append(tok)
    wall = 0.0
    tokens = 0
    while eng.num_active:
        t0 = time.perf_counter()
        _, blocks = eng.decode_block()
        wall += time.perf_counter() - t0
        for slot, toks in blocks.items():
            streams[slot].extend(toks)
            tokens += len(toks)
        for slot in list(range(eng.num_slots)):
            if eng.occupied[slot] and eng.decoding[slot] \
                    and eng.counts[slot] >= eng.budget[slot]:
                eng.evict(slot)
    return streams, wall, tokens


def run_sweep(*, slots: int, max_new: int, rank: int,
              smoke: bool) -> dict:
    import jax
    import numpy as np

    from tpudist.models import lora
    from tpudist.serve import SlotEngine

    module, params = _model()
    rng = np.random.default_rng(0)
    # one prompt LENGTH across slots (contents differ): the oracle's
    # cached generators then compile once per adapter, not per slot
    prompts = [rng.integers(0, CFG["vocab"], size=6).astype(np.int32)
               for s in range(slots)]
    budgets = [max_new] * slots
    factor_sets = {
        f"tenant-{i}": lora.make_adapter_factors(
            jax.random.PRNGKey(100 + i), module, rank, scale=0.2)
        for i in range(slots)}
    eng = SlotEngine(module, params, num_slots=slots, prefill_pad=8,
                     decode_block=8, paged=True, kv_block=8,
                     adapters=True, adapter_blocks=slots,
                     adapter_rank=rank)

    def arm(n_adapters: int):
        names = list(factor_sets)[:n_adapters]
        for n in names:
            eng.load_adapter(n, factor_sets[n])
        bound = [(names[s % n_adapters] if n_adapters else None)
                 for s in range(slots)]
        streams, wall, tokens = _run_arm(eng, prompts, budgets, bound)
        for n in names:
            eng.unload_adapter(n)
        return streams, wall, tokens, bound

    # warmup: one full-adapter cycle pays every XLA compile (the
    # twin-delta discipline — first-compile must not land in any arm)
    arm(slots)
    pins0 = dict(eng.compile_counts())
    # noise probe: one extra base-only run.  Together with the sweep's
    # own base arm below it yields two back-to-back measurements of the
    # SAME configuration; their ratio is pure run-to-run jitter (GC
    # pauses, CPU scheduler) at this shape, and the acceptance margin
    # is scaled by it so a lucky-fast base arm can't flunk every
    # adapter arm on a noisy host.
    _, _nw, _nt, _ = arm(0)
    probe_tps = (_nt / _nw) if _nw else None
    ks = sorted({0, 1, max(1, slots // 2), slots})
    rows = []
    outputs_match = True
    for k in ks:
        streams, wall, tokens, bound = arm(k)
        for s in range(slots):
            facs = factor_sets[bound[s]] if bound[s] else None
            ref = _oracle(module, params, prompts[s], budgets[s], facs,
                          bound[s] or "<base>")
            if streams[s] != ref:
                outputs_match = False
        rows.append({"adapters_per_batch": k,
                     "decode_tokens": tokens,
                     "decode_wall_s": round(wall, 6),
                     "tokens_per_s": round(tokens / wall, 2) if wall else None})
    pins1 = dict(eng.compile_counts())
    base = next(r for r in rows if r["adapters_per_batch"] == 0)
    ratios = {r["adapters_per_batch"]:
              round(r["tokens_per_s"] / base["tokens_per_s"], 4)
              for r in rows if r["adapters_per_batch"] > 0}
    ratio_min = min(ratios.values()) if ratios else None
    base_tps = base["tokens_per_s"]
    noise_floor = (round(min(base_tps, probe_tps)
                         / max(base_tps, probe_tps), 4)
                   if base_tps and probe_tps else 1.0)
    margin_used = round(max(MARGIN_FLOOR, MARGIN * noise_floor), 4)
    return {
        "rung": "adapter_sweep",
        "regime": "cpu" if jax.devices()[0].platform != "tpu" else "tpu",
        "note": ("tiny-model CPU mechanics — the cross-arm RATIOS on one "
                 "engine are the measurement, absolute tok/s is not"),
        "slots": slots, "max_new": max_new, "rank": rank,
        "smoke": bool(smoke),
        "rows": rows,
        "base_tokens_per_s": base["tokens_per_s"],
        "ratios_vs_base": ratios,
        "ratio_min": ratio_min,
        "margin": MARGIN,
        "noise_floor": noise_floor,
        "margin_used": margin_used,
        "within_margin": (ratio_min is not None
                          and ratio_min >= margin_used),
        "outputs_match": outputs_match,
        "compile_pins_flat": pins0 == pins1,
        "adapter_stats": {k: v for k, v in eng.adapter_stats().items()
                          if k in ("blocks_total", "rank", "block_bytes",
                                   "loads", "evicts", "unloads")},
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="CI-sized sweep (fewer decode tokens)")
    ap.add_argument("--out", default=None, help="output JSONL path")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-new", type=int, default=None)
    ap.add_argument("--rank", type=int, default=8)
    args = ap.parse_args(argv)
    max_new = args.max_new or (16 if args.smoke else 48)
    row = run_sweep(slots=args.slots, max_new=max_new, rank=args.rank,
                    smoke=args.smoke)
    line = json.dumps(row)
    print(line)
    if args.out:
        Path(args.out).write_text(line + "\n")
        print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
