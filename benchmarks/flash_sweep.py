#!/usr/bin/env python3
"""Flash-attention kernel tuning sweep: dense XLA vs Pallas blocks,
with an A/B column against JAX's stock TPU flash attention.

Times causal attention forward (and optionally fwd+bwd) at the demo shapes
(head_dim 64) across (block_q, block_k) and prints one JSON line per
configuration.  Run on the real chip; value-fetch synced (see bench.py).

The ``stock_flash`` rows time ``jax.experimental.pallas.ops.tpu``'s
shipped flash-attention kernel at the same geometry — the external
yardstick the in-house kernels are matched against (r5 verdict next #2:
beating your own history is not a perf claim).  Import- and
platform-guarded: on CPU CI or a jax build without the op the row
records WHY it was skipped instead of crashing the sweep.  Caveats
recorded in the row: the stock kernel has no sliding-window support
(window geometries skip it) and no GQA-native path (K/V are repeated to
full heads, so it pays MHA-equivalent bandwidth — that difference IS
the comparison).

Usage:
  python benchmarks/flash_sweep.py --seq 2048 --blocks 256x256,512x512
"""

from __future__ import annotations

import argparse
import functools
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

from tpudist.runtime.compilation_cache import enable_compilation_cache

enable_compilation_cache()
import jax.numpy as jnp
import numpy as np


def _time(fn, *args, steps=10):
    """Per-application seconds for ``fn``, measured as ONE dispatched XLA
    program that chains ``steps`` serially-dependent applications via
    lax.scan — per-call dispatch through the remote-execution tunnel is
    tens of ms, far more than the kernel itself, so timing separate calls
    measures the tunnel, not the op."""
    from jax import lax

    q0, rest = args[0], args[1:]

    @functools.partial(jax.jit, static_argnums=(0,))
    def chained(length, q, *rest):
        def body(carry, _):
            out = fn(carry, *rest)
            # feed the output back as q: same [b, h, s, d] shape, forces
            # serial execution of every application
            return out.reshape(carry.shape).astype(carry.dtype), ()

        final, _ = lax.scan(body, q, (), length=length)
        return final.sum()  # fetch one scalar, not MBs through the tunnel

    def once(length):
        out = chained(length, q0, *rest)
        float(jax.device_get(out))

    once(1)       # compile short program
    once(steps)   # compile long program

    # Two-point measurement: (t_long - t_short) cancels the fixed
    # dispatch/fetch overhead of the tunnel; min-of-repeats rejects
    # contention spikes (the tunnel is shared and noisy).
    short = long_ = float("inf")
    for _ in range(4):
        t0 = time.perf_counter()
        once(1)
        short = min(short, time.perf_counter() - t0)
        t0 = time.perf_counter()
        once(steps)
        long_ = min(long_, time.perf_counter() - t0)
    return (long_ - short) / (steps - 1)


def _stock_flash_fn(causal: bool):
    """Import the stock TPU flash-attention kernel, or explain why not.

    Returns ``(fn, None)`` with ``fn(q, k, v) -> out`` consuming
    full-head (MHA) inputs, or ``(None, reason)`` when the row must be
    skipped (non-TPU platform, missing module on this jax build)."""
    import jax as _jax

    if _jax.devices()[0].platform != "tpu":
        return None, "stock kernel runs on TPU only (CPU CI skips)"
    try:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as stock)
    except ImportError as e:
        return None, f"stock kernel unavailable on this jax: {e!r}"

    def fn(q, k, v):
        return stock(q, k, v, causal=causal)

    return fn, None


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--seq", default=2048, type=int)
    p.add_argument("--batch", default=4, type=int)
    p.add_argument("--heads", default=4, type=int)
    p.add_argument("--head-dim", default=64, type=int)
    p.add_argument("--kv-heads", default=None, type=int,
                   help="grouped-query KV head count (default = --heads)")
    p.add_argument("--window", default=None, type=int,
                   help="sliding-window band (band-tile DMA elision: cost "
                        "should scale with window, not seq)")
    p.add_argument("--blocks", default="128x128,256x256,256x512,512x512,512x1024,1024x1024")
    p.add_argument("--steps", default=10, type=int)
    p.add_argument("--grad", action="store_true", help="time fwd+bwd too")
    p.add_argument("--skip-dense", action="store_true")
    p.add_argument("--skip-stock", action="store_true",
                   help="drop the jax stock TPU flash-attention A/B rows")
    args = p.parse_args(argv)

    from tpudist.ops import flash_attention
    from tpudist.parallel.ring_attention import attention_reference

    rng = np.random.default_rng(0)
    kv_heads = args.heads if args.kv_heads is None else args.kv_heads
    if kv_heads < 1 or args.heads % kv_heads:
        raise SystemExit(
            f"--kv-heads {kv_heads} must be >= 1 and divide --heads {args.heads}")
    if args.window is not None and args.window < 1:
        raise SystemExit(f"--window must be >= 1, got {args.window}")
    shape = (args.batch, args.heads, args.seq, args.head_dim)
    kv_shape = (args.batch, kv_heads, args.seq, args.head_dim)
    q = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    k = jnp.asarray(rng.standard_normal(kv_shape), jnp.float32)
    v = jnp.asarray(rng.standard_normal(kv_shape), jnp.float32)

    results = []

    def report(name, secs):
        row = {"kernel": name, "seq": args.seq,
               "heads": args.heads, "kv_heads": kv_heads,
               "window": args.window, "ms": round(secs * 1e3, 3)}
        results.append(row)
        print(json.dumps(row))

    if not args.skip_dense:
        # GQA baseline: dense on repeated K/V — the MHA-equivalent compute
        # the grouped kernel's bandwidth win is measured against.
        group = args.heads // kv_heads
        kd = jnp.repeat(k, group, axis=1) if group > 1 else k
        vd = jnp.repeat(v, group, axis=1) if group > 1 else v
        dense = jax.jit(lambda a, b, c: attention_reference(
            a, b, c, causal=True, window=args.window))
        report("dense_xla_fwd", _time(dense, q, kd, vd, steps=args.steps))
        if args.grad:
            dense_g = jax.jit(jax.grad(
                lambda a, b, c: attention_reference(
                    a, b, c, causal=True, window=args.window).sum()
            ))
            report("dense_xla_fwdbwd", _time(dense_g, q, kd, vd, steps=args.steps))

    if not args.skip_stock:
        # A/B yardstick: jax's shipped TPU flash attention at the same
        # geometry (MHA-equivalent inputs — K/V repeated for GQA, like
        # the dense baseline above; it has no grouped-KV fast path).
        if args.window is not None:
            row = {"kernel": "stock_flash", "seq": args.seq,
                   "heads": args.heads, "kv_heads": kv_heads,
                   "window": args.window,
                   "skipped": "stock kernel has no sliding-window support"}
            results.append(row)
            print(json.dumps(row))
        else:
            stock, reason = _stock_flash_fn(causal=True)
            if stock is None:
                row = {"kernel": "stock_flash", "seq": args.seq,
                       "heads": args.heads, "kv_heads": kv_heads,
                       "window": args.window, "skipped": reason}
                results.append(row)
                print(json.dumps(row))
            else:
                group = args.heads // kv_heads
                ks = jnp.repeat(k, group, axis=1) if group > 1 else k
                vs = jnp.repeat(v, group, axis=1) if group > 1 else v
                st = jax.jit(stock)
                report("stock_flash_fwd", _time(st, q, ks, vs,
                                                steps=args.steps))
                if args.grad:
                    st_g = jax.jit(jax.grad(
                        lambda a, b, c: stock(a, b, c).sum()))
                    report("stock_flash_fwdbwd",
                           _time(st_g, q, ks, vs, steps=args.steps))

    for spec in args.blocks.split(","):
        bq, bk = (int(x) for x in spec.split("x"))
        if args.seq % bq or args.seq % bk:
            continue
        fl = jax.jit(lambda a, b, c, bq=bq, bk=bk:
                     flash_attention(a, b, c, True, bq, bk, False,
                                     args.window))
        report(f"flash_{bq}x{bk}_fwd", _time(fl, q, k, v, steps=args.steps))
        if args.grad:
            fl_g = jax.jit(jax.grad(
                lambda a, b, c, bq=bq, bk=bk:
                flash_attention(a, b, c, True, bq, bk, False,
                                args.window).sum()
            ))
            report(f"flash_{bq}x{bk}_fwdbwd", _time(fl_g, q, k, v, steps=args.steps))
    return results


if __name__ == "__main__":
    main()
