#!/usr/bin/env python3
"""Multi-turn-session + overload bench: the graceful-degradation rungs,
frozen per round as ``BENCH_SESSION_r{NN}.json``.

Three rungs, all CPU-safe (tiny model; absolute times are interpreter
mechanics — the TWIN DELTAS are the measurements):

- **session_twin** — S sessions × T turns, interleaved rounds at pool ≪
  sessions, identical schedules served twice: host tier ON (turn ≥ 2
  resumes its parked KV, teacher-forcing only the new suffix) vs OFF
  (every turn re-prefills its whole context).  Quotes resume-TTFT vs
  re-prefill-TTFT and asserts the two arms' outputs are byte-equal —
  the no-recompute claim measured, not assumed.

- **overload_shed** — a declared TTFT SLO + the live per-tenant
  attainment gauges, bulk flood vs paced protected ("gold") traffic,
  served twice: shedding ON vs OFF.  With shedding, the first measured
  violations trip the controller (``shed_state`` events carry the gauge
  readings that drove it — the decision is auditable), bulk stops
  admitting, and the protected tenant's attainment recovers; without,
  it stays degraded.  The artifact freezes both attainments plus the
  shed counters.

- **preempt_twin** — one decode slot, a long low-priority decode, a
  high-priority arrival: host tier ON (the bulk lane parks mid-stream,
  gold starts immediately, bulk resumes byte-identically) vs OFF (gold
  waits out the bulk lane).  Quotes gold TTFT under preemption vs
  waiting, and the preemption count.

Usage: ``python benchmarks/session_bench.py [--smoke] [--out PATH]``
(round_snapshot.py freezes it per round; the tier-1 smoke test asserts
the rung fields).
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

CFG = dict(vocab=32, d_model=32, n_layers=2, n_heads=2, d_ff=64,
           max_len=64)


def _model(seed: int = 0):
    import jax

    from tpudist.models import create_transformer

    return create_transformer(jax.random.PRNGKey(seed), seq_len=16, **CFG)


def _mean(vals):
    vals = [v for v in vals if v is not None]
    return sum(vals) / len(vals) if vals else None


def _p50(vals):
    vals = sorted(v for v in vals if v is not None)
    return vals[len(vals) // 2] if vals else None


# ---------------------------------------------------------------------------
# session_twin


def _run_session_arm(model, *, host_tier: bool, sessions: int, turns: int,
                     new_tokens: int, max_new: int) -> dict:
    """One arm of the session twin: the SAME deterministic turn
    schedule (interleaved rounds — every session's turn t lands
    together, so the pool churns at pool ≪ sessions), with the tier on
    or off."""
    import numpy as np

    from tpudist.serve import InferenceServer, ServeConfig

    cfg = ServeConfig(num_slots=2, max_new=max_new, prefill_pad=4,
                      queue_limit=max(16, 2 * sessions),
                      paged=True, kv_block=8,
                      host_tier=host_tier)
    srv = InferenceServer(*model, cfg,
                          install_signal_handler=False).start()
    rng = np.random.default_rng(0)
    # long opening context on purpose: the tier's win is skipping the
    # covered prefix's recompute, so the measured delta must not drown
    # in fixed per-turn overhead at toy context lengths
    contexts = {s: rng.integers(0, CFG["vocab"], size=24).astype(np.int32)
                for s in range(sessions)}
    ttft_by_turn: dict = {t: [] for t in range(turns)}
    reasons: dict = {}
    outputs = []
    try:
        # warmup session (not measured): pays every XLA compile the
        # arm will use — insert/prefill/decode/evict, and on the tier
        # arm export_lane/import_lane too — so the twin delta measures
        # recompute, not first-compile
        warm = srv.submit(contexts[0][:8], max_new=2, session="warm")
        assert warm.wait(600)
        warm2 = srv.submit(
            np.concatenate([contexts[0][:8],
                            np.asarray(warm.tokens, np.int32),
                            contexts[0][:4]]),
            max_new=2, session="warm")
        assert warm2.wait(600)
        for t in range(turns):
            handles = []
            for s in range(sessions):
                if t > 0:
                    new = rng.integers(0, CFG["vocab"],
                                       size=new_tokens).astype(np.int32)
                    contexts[s] = np.concatenate([contexts[s], new])
                handles.append(
                    (s, srv.submit(contexts[s], max_new=max_new,
                                   session=f"s{s}", tenant="bench")))
            for s, h in handles:
                assert h.wait(600), "session turn timed out"
                ttft_by_turn[t].append(h.ttft_s)
                reasons[h.finish_reason] = reasons.get(h.finish_reason,
                                                       0) + 1
                outputs.append((t, s, list(h.tokens)))
                contexts[s] = np.concatenate(
                    [contexts[s], np.asarray(h.tokens, np.int32)])
        # let the final round's parks land on the engine thread
        deadline = time.monotonic() + 5
        while (host_tier and srv._tier.parks < sessions
               and time.monotonic() < deadline):
            time.sleep(0.01)
        tier = dict(srv._tier.stats()) if host_tier else None
    finally:
        srv.close(60)
    later = [v for t in range(1, turns) for v in ttft_by_turn[t]]
    return {
        "ttft_turn1_s": _mean(ttft_by_turn[0]),
        "ttft_later_mean_s": _mean(later),
        "ttft_later_p50_s": _p50(later),
        "finish_reasons": reasons,
        "tier": tier,
        "outputs": outputs,
    }


def run_session_twin(sessions: int, turns: int) -> dict:
    model = _model()
    new_tokens, max_new = 4, 6
    on = _run_session_arm(model, host_tier=True, sessions=sessions,
                          turns=turns, new_tokens=new_tokens,
                          max_new=max_new)
    off = _run_session_arm(model, host_tier=False, sessions=sessions,
                           turns=turns, new_tokens=new_tokens,
                           max_new=max_new)
    resumed = on["finish_reasons"].get("session_resumed", 0)
    return {
        "rung": "session_twin",
        "regime": "cpu-smoke",
        "sessions": sessions,
        "turns": turns,
        "pool_slots": 2,
        "resume_ttft_s": on["ttft_later_mean_s"],
        "resume_ttft_p50_s": on["ttft_later_p50_s"],
        "reprefill_ttft_s": off["ttft_later_mean_s"],
        "reprefill_ttft_p50_s": off["ttft_later_p50_s"],
        "resume_speedup": (off["ttft_later_mean_s"]
                           / on["ttft_later_mean_s"]
                           if on["ttft_later_mean_s"] else None),
        "turns_resumed": resumed,
        "turns_expected_resumed": sessions * (turns - 1),
        # the correctness half: identical greedy outputs across arms —
        # resume must be a latency lever, never a numerics one
        "outputs_match": on["outputs"] == off["outputs"],
        "finish_reasons_on": on["finish_reasons"],
        "tier": on["tier"],
        "note": ("same deterministic turn schedule both arms; CPU "
                 "absolute TTFT is interpreter mechanics — the on/off "
                 "delta is the recompute the tier skips"),
    }


# ---------------------------------------------------------------------------
# overload_shed


def _run_overload_arm(model, *, shed: bool, rounds: int,
                      bulk_per_round: int, slo_ms: float) -> dict:
    import numpy as np

    from tpudist import telemetry
    from tpudist.serve import InferenceServer, ServeConfig
    from tpudist.serve.scheduler import AdmissionError
    from tpudist.telemetry import metrics

    # the SLO gauges feed off the telemetry event seam — the arm needs
    # a LIVE session (request_finished → feed_record → attainment
    # gauge → the controller's read), scoped to this arm
    saved_tel = os.environ.get("TPUDIST_TELEMETRY")
    os.environ["TPUDIST_TELEMETRY"] = "1"
    tdir = (Path(os.environ.get("TPUDIST_TELEMETRY_DIR", "runs/telemetry"))
            / f"session_bench_{'shed' if shed else 'noshed'}")
    telemetry.start(str(tdir), rank=0, generation=0)
    metrics.registry().clear()
    metrics.arm_from_env()
    cfg = ServeConfig(num_slots=2, max_new=48, prefill_pad=8,
                      decode_block=1, queue_limit=16,
                      shed=shed, shed_attainment=0.9, shed_priority=1)
    srv = InferenceServer(*model, cfg,
                          install_signal_handler=False).start()
    rng = np.random.default_rng(1)
    gold_ttfts, bulk_handles = [], []
    bulk_rejected: dict = {}
    try:
        # warmup (untargeted tenant, never measured): pays the XLA
        # compiles so round-1 gold TTFT measures scheduling, not compile
        warm = srv.submit(rng.integers(0, CFG["vocab"], size=4)
                          .astype(np.int32), max_new=4, tenant="warm",
                          priority=0)
        assert warm.wait(600)
        for _ in range(rounds):
            for _ in range(bulk_per_round):
                p = rng.integers(0, CFG["vocab"], size=4).astype(np.int32)
                try:
                    bulk_handles.append(
                        srv.submit(p, max_new=48, priority=0,
                                   tenant="bulk"))
                except AdmissionError as e:
                    key = e.reason.split(":")[0]
                    bulk_rejected[key] = bulk_rejected.get(key, 0) + 1
            # wait for the bulk wave to actually OCCUPY the slots (the
            # overload condition) before the protected arrival; under
            # active shedding nothing admits — the short timeout then
            # just lets the healthy gold through
            t0 = time.monotonic()
            while (srv.engine.num_active < cfg.num_slots
                   and time.monotonic() - t0 < 0.25):
                time.sleep(0.002)
            g = rng.integers(0, CFG["vocab"], size=4).astype(np.int32)
            gold = srv.submit(g, max_new=6, priority=2, tenant="gold")
            assert gold.wait(600), "gold request timed out"
            gold_ttfts.append(gold.ttft_s)
        attain = metrics.slo_attainment().get(("ttft", "gold"))
        ctrl = srv._ctrl.stats() if srv._ctrl is not None else None
    finally:
        srv.close(120)
        telemetry.finish(write_report=False)
        if saved_tel is None:
            os.environ.pop("TPUDIST_TELEMETRY", None)
        else:
            os.environ["TPUDIST_TELEMETRY"] = saved_tel
    shed_finished = sum(1 for h in bulk_handles
                        if h.finish_reason == "shed_load")
    return {
        "gold_ttft_mean_s": _mean(gold_ttfts),
        "gold_ttft_p50_s": _p50(gold_ttfts),
        "gold_attainment": attain,
        "gold_violations": sum(1 for v in gold_ttfts
                               if v is not None and v > slo_ms / 1e3),
        "bulk_submitted": len(bulk_handles),
        "bulk_rejected": bulk_rejected,
        "bulk_shed": shed_finished,
        "controller": ctrl,
    }


def _calibrate_slo(model) -> dict:
    """Measure THIS rig's healthy (idle-server) and blocked
    (slots-full-of-bulk) gold TTFT and put the declared target at their
    geometric midpoint — the rung then tests the shed MECHANISM, not a
    hard-coded latency guess that a faster/slower rig would invalidate
    (measure, then schedule — the bench applies its own lesson)."""
    import numpy as np

    from tpudist.serve import InferenceServer, ServeConfig

    cfg = ServeConfig(num_slots=2, max_new=48, prefill_pad=8,
                      decode_block=1, queue_limit=16)
    srv = InferenceServer(*model, cfg,
                          install_signal_handler=False).start()
    rng = np.random.default_rng(9)

    def _gold():
        h = srv.submit(rng.integers(0, CFG["vocab"], size=4)
                       .astype(np.int32), max_new=6, priority=2)
        assert h.wait(600)
        return h.ttft_s

    try:
        _gold()  # warmup (compiles)
        healthy = _p50([_gold() for _ in range(3)])
        bulks = [srv.submit(rng.integers(0, CFG["vocab"], size=4)
                            .astype(np.int32), max_new=48, priority=0)
                 for _ in range(2)]
        t0 = time.monotonic()
        while (srv.engine.num_active < 2
               and time.monotonic() - t0 < 2.0):
            time.sleep(0.002)
        blocked = _gold()
        for b in bulks:
            b.wait(600)
    finally:
        srv.close(60)
    return {"healthy_s": healthy, "blocked_s": blocked,
            "slo_ms": (healthy * blocked) ** 0.5 * 1e3,
            "degenerate": blocked < 2.5 * healthy}


def run_overload_shed(rounds: int, bulk_per_round: int) -> dict:
    """Shed ON vs OFF on the same bulk-flood + paced-gold schedule.
    The declared target sits between this rig's MEASURED healthy and
    blocked gold TTFT, so the degraded arm violates and the shed arm
    recovers — driven by the LIVE attainment gauge (the controller
    reads ``metrics.slo_attainment()``, and every flip is stamped with
    the readings)."""
    model = _model()
    cal = _calibrate_slo(model)
    slo_ms = cal["slo_ms"]
    saved = {k: os.environ.get(k)
             for k in ("TPUDIST_SLO_TTFT_MS", "TPUDIST_SLO_TPOT_MS",
                       "TPUDIST_METRICS")}
    os.environ["TPUDIST_SLO_TTFT_MS"] = str(slo_ms)
    os.environ.pop("TPUDIST_SLO_TPOT_MS", None)
    os.environ["TPUDIST_METRICS"] = "1"
    try:
        protected = _run_overload_arm(model, shed=True, rounds=rounds,
                                      bulk_per_round=bulk_per_round,
                                      slo_ms=slo_ms)
        degraded = _run_overload_arm(model, shed=False, rounds=rounds,
                                     bulk_per_round=bulk_per_round,
                                     slo_ms=slo_ms)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        from tpudist.telemetry import metrics

        metrics.registry().clear()
        metrics.arm_from_env()
    ctrl = protected["controller"] or {}
    return {
        "rung": "overload_shed",
        "regime": "cpu-smoke",
        "slo_ttft_ms": round(slo_ms, 3),
        "calibration": {k: (round(v, 6) if isinstance(v, float) else v)
                        for k, v in cal.items()},
        "rounds": rounds,
        "bulk_per_round": bulk_per_round,
        "gold_attainment_protected": protected["gold_attainment"],
        "gold_attainment_degraded": degraded["gold_attainment"],
        "gold_ttft_protected_s": protected["gold_ttft_mean_s"],
        "gold_ttft_degraded_s": degraded["gold_ttft_mean_s"],
        "bulk_shed": protected["bulk_shed"],
        "bulk_rejected_shed_load":
            protected["bulk_rejected"].get("shed_load", 0),
        "shed_state_changes": ctrl.get("flips", 0),
        # the audit trail: the readings the controller acted on — the
        # "driven by the live gauges" proof riding in the artifact
        "shed_driven_by_gauge": bool(ctrl.get("flips", 0)
                                     and ctrl.get("last_attainment")),
        "last_attainment_readings": ctrl.get("last_attainment"),
        "protected_recovers": (
            protected["gold_attainment"] is not None
            and degraded["gold_attainment"] is not None
            and protected["gold_attainment"]
            > degraded["gold_attainment"]),
        "note": ("same schedule both arms; the shed arm's controller "
                 "reads the live tpudist_slo_attainment gauge and "
                 "stops admitting bulk once the protected tenant "
                 "violates — its cumulative attainment then recovers "
                 "while the degraded arm's stays down"),
    }


# ---------------------------------------------------------------------------
# preempt_twin


def _run_preempt_arm(model, *, host_tier: bool) -> dict:
    import numpy as np

    from tpudist.serve import InferenceServer, ServeConfig

    cfg = ServeConfig(num_slots=1, max_new=56, prefill_pad=8,
                      decode_block=1, host_tier=host_tier)
    srv = InferenceServer(*model, cfg,
                          install_signal_handler=False).start()
    rng = np.random.default_rng(2)

    def _cycle():
        bulk = srv.submit(rng.integers(0, CFG["vocab"], size=4)
                          .astype(np.int32), max_new=56, priority=0)
        while len(bulk.tokens) < 3:
            time.sleep(0.002)
        gold = srv.submit(rng.integers(0, CFG["vocab"], size=4)
                          .astype(np.int32), max_new=6, priority=2)
        assert gold.wait(600) and bulk.wait(600)
        return gold, bulk

    try:
        _cycle()  # warmup: pays every compile (export/import included
        # on the tier arm), so the measured twin delta is the
        # scheduling decision, not first-compile
        gold, bulk = _cycle()
        return {"gold_ttft_s": gold.ttft_s,
                "preemptions": srv.preemptions,
                "bulk_tokens": len(bulk.tokens),
                "bulk_reason": bulk.finish_reason}
    finally:
        srv.close(60)


def run_preempt_twin() -> dict:
    model = _model()
    on = _run_preempt_arm(model, host_tier=True)
    off = _run_preempt_arm(model, host_tier=False)
    return {
        "rung": "preempt_twin",
        "regime": "cpu-smoke",
        "gold_ttft_preempt_s": on["gold_ttft_s"],
        "gold_ttft_wait_s": off["gold_ttft_s"],
        "preempt_speedup": (off["gold_ttft_s"] / on["gold_ttft_s"]
                            if on["gold_ttft_s"] else None),
        "preemptions": on["preemptions"],
        "bulk_completed_after_resume":
            on["bulk_tokens"] == 56 and on["bulk_reason"] == "length",
        "note": ("1 decode slot, 56-token low-priority decode; with the "
                 "tier the high-priority arrival parks it mid-stream "
                 "and starts immediately — bulk still completes its "
                 "full byte-identical stream after resume"),
    }


# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--smoke", action="store_true",
                   help="CI scale (tiny counts; same rung structure)")
    p.add_argument("--sessions", type=int, default=None)
    p.add_argument("--turns", type=int, default=None)
    p.add_argument("--rounds", type=int, default=None)
    p.add_argument("--out", default=None)
    args = p.parse_args(argv)

    sessions = args.sessions or (6 if args.smoke else 16)
    turns = args.turns or (3 if args.smoke else 4)
    rounds = args.rounds or (6 if args.smoke else 12)

    # keep the bench hermetic in-process (the tier-1 smoke test calls
    # main() directly): silence the post-hoc stream unless the caller
    # routed it somewhere
    saved_tel = os.environ.get("TPUDIST_TELEMETRY")
    if "TPUDIST_TELEMETRY_DIR" not in os.environ:
        os.environ["TPUDIST_TELEMETRY"] = "0"
    rows = []
    try:
        rows.append(run_session_twin(sessions, turns))
        print(json.dumps(rows[-1]))
        rows.append(run_overload_shed(rounds, bulk_per_round=3))
        print(json.dumps(rows[-1]))
        rows.append(run_preempt_twin())
        print(json.dumps(rows[-1]))
    finally:
        if saved_tel is None:
            os.environ.pop("TPUDIST_TELEMETRY", None)
        else:
            os.environ["TPUDIST_TELEMETRY"] = saved_tel
    if args.out:
        out = Path(args.out)
        out.parent.mkdir(parents=True, exist_ok=True)
        with out.open("w") as f:
            for r in rows:
                # the artifact drops the per-token output dump (it is
                # only for the cross-arm equality check)
                slim = {k: v for k, v in r.items() if k != "outputs"}
                f.write(json.dumps(slim) + "\n")
        print(json.dumps({"wrote": str(out)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
