#!/usr/bin/env python3
"""TRUE multi-process scaling rungs — through the tpurun agent, across
real process boundaries (VERDICT r4 next #3).

``benchmarks/scaling.py`` measures virtual-device rungs inside ONE
process; its n=8 "efficiency 0.051" is CPU-core contention, not framework
behavior, and reads like a scaling collapse.  This harness measures what
that artifact cannot: the cost of crossing PROCESS boundaries — gloo
rendezvous, cross-process gradient collectives, per-process loader work,
and host-fabric metric reductions — at n_proc ∈ {1, 2, 4}, each process
one JAX CPU device, launched by ``python -m tpudist.launch`` exactly like
a real multi-host job (the reference's de-facto scaling check is the same
shape: real srun ranks, ``salloc_torchrun.sh:40-49``).

Contention correction.  On a host with ``c`` cores, weak-scaling ideal
aggregate throughput is ``agg_1 × min(n, c)`` — adding processes beyond
the core count cannot add compute, only overhead.  The honest column is

    corrected_efficiency = agg_n / (agg_1 × min(n, c))

= 1.0 when process boundaries cost nothing (all compute serialized but
preserved), < 1 exactly by the framework's coordination overhead.  On a
multi-core host it degenerates to the naive efficiency; on this 1-core
bench container it isolates overhead from fake "collapse".

Per-rung overhead split (slowest-rank times, per iteration):
  step_ms    compiled DP step on a pre-placed batch (includes the
             cross-process gradient all-reduce at n > 1)
  loader_ms  ShardedLoader epoch iteration (host-side shard/shuffle)
  e2e_ms     loader + shard_batch placement + step (the real loop body)
  metric_ms  host-fabric (gloo) scalar loss all-reduce (demo.py:84's
             second-fabric analog)

Null-step calibration.  Before each width's real rung, a calibration
rung runs barrier + host scalar all-reduce ONLY — no compute, no
loader, no jax step — pricing the coordination floor of this rig
(loopback-TCP handshakes + scheduler wake-ups).  The real rung's
in-step collective estimate is then reported twice: raw
(``collective_ms_per_step_est``) and with the same-width floor
subtracted (``collective_ms_per_step_cal``), so the framework is
charged for gradient data movement, never for handshake latency any
null step at that width would also pay (``--skip-null`` drops the
calibration rungs and the calibrated column).

Writes the detailed artifact to ``SCALING_MULTIPROC_r{NN}.json`` (NN =
the round being built).  Per-rung progress goes to STDERR as each rung
finishes; STDOUT carries only the final enriched rows (with the
efficiency columns) plus the summary — that is what
``benchmarks/round_snapshot.py`` merges into ``SCALING_r{NN}.json``
next to the virtual-cpu regime.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import textwrap
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

WORKER = """
import json, os, time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)  # 1 device per process
os.environ["JAX_CPU_COLLECTIVES_IMPLEMENTATION"] = "gloo"
# thread pinning: one intra-op thread per process — rungs differ only in
# process count, not in per-process thread budget
os.environ.setdefault("OMP_NUM_THREADS", "1")

import numpy as np
import jax
import optax

from tpudist.comm import collectives
from tpudist.data import ShardPlan, make_loader, make_toy_data
from tpudist.data.loader import shard_batch
from tpudist.models import create_toy_model
from tpudist.runtime import bootstrap
from tpudist.runtime.mesh import data_parallel_mesh
from tpudist.train import init_model_states, make_multi_model_train_step
from tpudist.train.step import batch_sharding

ITERS = int(os.environ["SCALE_ITERS"])
BATCH = int(os.environ["SCALE_BATCH_PER_PROC"])

ctx = bootstrap.initialize()
n = ctx.num_processes

if os.environ.get("SCALE_NULL") == "1":
    # Null-step calibration rung: coordination floor only — barrier +
    # host scalar all-reduce, no compute, no loader, no jax step.  What
    # this prices is the fixed per-handshake cost of crossing process
    # boundaries on THIS rig (gloo over loopback TCP plus scheduler
    # wake-ups when procs > cores); the real rungs subtract it from
    # their collective term so the reported number is data movement +
    # framework work, not the handshake floor every rung pays anyway.
    collectives.barrier("scale_null_warm")
    t0 = time.perf_counter()
    for _ in range(ITERS):
        collectives.barrier("scale_null")
        collectives.host_allreduce_sum(np.float64(1.0))
    t_null = time.perf_counter() - t0
    out = {
        "rank": ctx.process_id,
        "n_procs": n,
        "iters": ITERS,
        "null_ms": t_null / ITERS * 1e3,
    }
    path = os.path.join(os.environ["SCALE_OUT"],
                        f"rank{ctx.process_id}.json")
    with open(path, "w") as f:
        json.dump(out, f)
    bootstrap.shutdown()
    raise SystemExit(0)

mesh = data_parallel_mesh()

kx, ky = jax.random.split(jax.random.PRNGKey(0))
mx, px = create_toy_model(kx)
my, py = create_toy_model(ky)
models = {"model_X": (mx.apply, px), "model_Y": (my.apply, py)}
tx = optax.adam(1e-3)
states = init_model_states(models, tx)
step = make_multi_model_train_step(
    {k: f for k, (f, _) in models.items()}, tx, mesh)

data = make_toy_data(n=max(512, BATCH * n * 2), seed=0)
plan = ShardPlan(num_samples=len(data), num_shards=n,
                 shard_id=ctx.process_id, shuffle=True, seed=0,
                 mode="distributed")
loader = make_loader(data, BATCH, plan)
sharding = batch_sharding(mesh)

def one_batch():
    loader.set_epoch(0)
    return next(iter(loader))

# warmup: compile + first collective
x0, y0 = one_batch()
gx, gy = shard_batch((x0, y0), sharding)
for _ in range(3):
    states, losses = step(states, gx, gy)
jax.block_until_ready(losses)
collectives.barrier("scale_warm")

# 1. compiled-step loop (fixed pre-placed batch): DP fabric cost
t0 = time.perf_counter()
for _ in range(ITERS):
    states, losses = step(states, gx, gy)
jax.block_until_ready(losses)
t_step = time.perf_counter() - t0

# 2. loader-only: host-side shard/shuffle/slice work
epoch = 0
t0 = time.perf_counter()
got = 0
while got < ITERS:
    loader.set_epoch(epoch)
    for xb, yb in loader:
        got += 1
        if got >= ITERS:
            break
    epoch += 1
t_loader = time.perf_counter() - t0

# 3. end-to-end loop body: loader + global placement + step
epoch = 0
got = 0
t0 = time.perf_counter()
while got < ITERS:
    loader.set_epoch(epoch)
    for xb, yb in loader:
        if got >= ITERS:
            break
        bx, by = shard_batch((xb, yb), sharding)
        states, losses = step(states, bx, by)
        got += 1
    epoch += 1
jax.block_until_ready(losses)
t_e2e = time.perf_counter() - t0

# 4. host-fabric metric reduction (the second-Gloo-group analog)
loss_host = float(jax.device_get(losses["model_X"]))
t0 = time.perf_counter()
for _ in range(ITERS):
    collectives.host_allreduce_sum(np.float64(loss_host))
t_metric = time.perf_counter() - t0

out = {
    "rank": ctx.process_id,
    "n_procs": n,
    "iters": ITERS,
    "batch_per_proc": BATCH,
    "step_ms": t_step / ITERS * 1e3,
    "loader_ms": t_loader / ITERS * 1e3,
    "e2e_ms": t_e2e / ITERS * 1e3,
    "metric_ms": t_metric / ITERS * 1e3,
}
path = os.path.join(os.environ["SCALE_OUT"], f"rank{ctx.process_id}.json")
with open(path, "w") as f:
    json.dump(out, f)
bootstrap.shutdown()
"""


def run_rung(n_procs: int, *, iters: int, batch_per_proc: int,
             null: bool = False) -> dict:
    from tpudist.launch.run import main as tpurun_main

    saved_env = dict(os.environ)
    with tempfile.TemporaryDirectory() as td:
        worker = Path(td) / "worker.py"
        worker.write_text(textwrap.dedent(WORKER))
        out_dir = Path(td) / "out"
        out_dir.mkdir()
        try:
            # scrub launcher env so each rung rendezvouses fresh
            # (restored below — the calling process, e.g. a pytest
            # session under SLURM, must keep its launch contract)
            for var in list(os.environ):
                if var.startswith(("TPUDIST_", "SLURM_", "OMPI_")) or var in (
                        "RANK", "WORLD_SIZE", "MASTER_ADDR", "NODE_RANK"):
                    os.environ.pop(var, None)
            os.environ["SCALE_OUT"] = str(out_dir)
            os.environ["SCALE_ITERS"] = str(iters)
            os.environ["SCALE_BATCH_PER_PROC"] = str(batch_per_proc)
            if null:
                os.environ["SCALE_NULL"] = "1"
            os.environ["PYTHONPATH"] = (
                str(REPO) + os.pathsep + saved_env["PYTHONPATH"]
                if "PYTHONPATH" in saved_env else str(REPO))
            t0 = time.perf_counter()
            rc = tpurun_main([
                "--nprocs", str(n_procs), "--max-restarts", "0",
                "--tmpdir", str(Path(td) / "scratch"),
                "--", sys.executable, str(worker),
            ])
            wall = time.perf_counter() - t0
        finally:
            os.environ.clear()
            os.environ.update(saved_env)
        if rc != 0:
            return {"n_procs": n_procs, "error": f"tpurun rc={rc}"}
        recs = [json.load(open(f)) for f in sorted(out_dir.glob("rank*.json"))]
    if len(recs) != n_procs:
        # A rank that crashed after tpurun exited 0 leaves fewer records;
        # follow the harness's error-row convention (like rc != 0 above)
        # so later rungs still run and the artifact is still written.
        return {"n_procs": n_procs,
                "error": f"expected {n_procs} rank records, "
                         f"found {len(recs)}"}
    # slowest rank bounds the job — that IS the distributed cost
    if null:
        worst_null = max(r["null_ms"] for r in recs)
        return {
            "regime": "multiprocess-cpu-null",
            "n_procs": n_procs,
            "iters": iters,
            "null_ms": round(worst_null, 3),
            "rendezvous_plus_run_wall_s": round(wall, 1),
        }
    worst = {k: max(r[k] for r in recs)
             for k in ("step_ms", "loader_ms", "e2e_ms", "metric_ms")}
    agg = n_procs * batch_per_proc / (worst["e2e_ms"] / 1e3)
    agg_step_only = n_procs * batch_per_proc / (worst["step_ms"] / 1e3)
    return {
        "regime": "multiprocess-cpu",
        "n_procs": n_procs,
        "iters": iters,
        "batch_per_proc": batch_per_proc,
        **{k: round(v, 3) for k, v in worst.items()},
        "agg_samples_per_sec": round(agg, 1),
        "agg_samples_per_sec_step_only": round(agg_step_only, 1),
        "rendezvous_plus_run_wall_s": round(wall, 1),
    }


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--n-procs", default="1,2,4")
    p.add_argument("--iters", type=int, default=64)
    p.add_argument("--batch-per-proc", type=int, default=256)
    p.add_argument("--skip-null", action="store_true",
                   help="drop the null-step calibration rungs (the "
                        "calibrated collective column is then absent)")
    # Detailed artifact (columns doc + interpretation).  The round
    # snapshot merges this harness's rung LINES into SCALING_r{NN}.json
    # next to the virtual-cpu regime (benchmarks/round_snapshot.py).
    # Default round = the one being built, so a standalone run never
    # clobbers a frozen round (benchmarks/_round.py; REPO is on sys.path).
    from benchmarks._round import current_round

    p.add_argument(
        "--out",
        default=str(REPO / f"SCALING_MULTIPROC_r{current_round():02d}.json"))
    args = p.parse_args(argv)

    cores = os.cpu_count() or 1
    rungs = []
    calibration = []
    null_ms_by_n: dict[int, float] = {}
    for n in [int(x) for x in args.n_procs.split(",")]:
        # The gate (VERDICT Weak #4): a rung with n_procs > host_cores
        # measures the host scheduler time-slicing collective partners,
        # not the framework — it still runs (its row is the honest
        # upper bound the interpretation note describes) but carries
        # the "scheduler-bound" label so no reader quotes it as a
        # scaling number, and the summary excludes it from the
        # efficiency claim.
        scheduler_bound = n > cores
        if not args.skip_null:
            # Null-step calibration FIRST at each width: barrier + host
            # scalar all-reduce only, no compute — the coordination
            # floor every rung at this width pays regardless of the
            # framework.  A failed calibration is an error row, never a
            # dead harness: the real rung still runs, its calibrated
            # column is just absent.
            c = run_rung(n, iters=args.iters,
                         batch_per_proc=args.batch_per_proc, null=True)
            if scheduler_bound and "error" not in c:
                c["label"] = "scheduler-bound"
            calibration.append(c)
            if "error" not in c:
                null_ms_by_n[n] = c["null_ms"]
            print(json.dumps(c), file=sys.stderr, flush=True)
        r = run_rung(n, iters=args.iters, batch_per_proc=args.batch_per_proc)
        if "error" not in r and n in null_ms_by_n:
            r["null_coordination_ms"] = null_ms_by_n[n]
        if scheduler_bound and "error" not in r:
            r["scheduler_bound"] = True
            r["label"] = "scheduler-bound"
        rungs.append(r)
        # progress to stderr; stdout carries only the FINAL enriched rows
        # (round_snapshot merges stdout lines into SCALING_r{NN}.json,
        # which must show the corrected-efficiency columns)
        print(json.dumps(r), file=sys.stderr, flush=True)

    ok = [r for r in rungs if "error" not in r]
    base = next((r for r in ok if r["n_procs"] == 1), None)
    if base:
        for r in ok:
            n = r["n_procs"]
            # Weak-scaling contention ideal: per-proc work is constant,
            # so n procs on c cores take base x n/min(n, c) per
            # iteration (x1 while cores cover the procs, x n/c once
            # they oversubscribe).  Both overhead columns subtract THIS
            # ideal — core contention must never be misattributed to
            # framework/collective overhead.
            ideal_factor = n / min(n, cores)
            ideal = base["agg_samples_per_sec"] * min(n, cores)
            r["naive_efficiency_vs_1"] = round(
                r["agg_samples_per_sec"]
                / (base["agg_samples_per_sec"] * n), 3)
            r["contention_corrected_efficiency"] = round(
                r["agg_samples_per_sec"] / ideal, 3)
            r["boundary_overhead_ms"] = round(
                r["e2e_ms"] - ideal_factor * base["e2e_ms"], 3)
            # the dominant term, named: the in-step cross-process
            # collective
            r["collective_ms_per_step_est"] = round(
                max(r["step_ms"] - ideal_factor * base["step_ms"], 0.0), 3)
            if n in null_ms_by_n:
                # calibrated: the null-step coordination floor (barrier
                # + host all-reduce at the SAME width, measured this
                # session) subtracted — what remains is gradient-bytes
                # movement + framework work, not handshake latency.
                r["collective_ms_per_step_cal"] = round(
                    max(r["collective_ms_per_step_est"]
                        - null_ms_by_n[n], 0.0), 3)
    out = {
        "regime": "multiprocess-cpu",
        "host_cores": cores,
        "launched_via": "python -m tpudist.launch (tpurun agent), "
                        "1 JAX CPU device + OMP_NUM_THREADS=1 per process, "
                        "gloo cross-process collectives",
        "columns": {
            "naive_efficiency_vs_1": "agg_n / (agg_1 * n) — meaningless "
                "when n exceeds host cores (reads as collapse)",
            "contention_corrected_efficiency": "agg_n / (agg_1 * min(n, "
                "cores)) — 1.0 = process boundaries cost nothing; the "
                "shortfall is rendezvous + collective + loader + "
                "placement overhead, not core sharing",
            "boundary_overhead_ms": "e2e_ms beyond the contention-ideal "
                "(1-core: n * e2e_ms_1) per iteration",
            "collective_ms_per_step_est": "step_ms beyond the "
                "contention-ideal step — the in-step cross-process "
                "gradient reduce on this rig",
            "null_coordination_ms": "null-step calibration at the same "
                "width: barrier + host scalar all-reduce per iteration, "
                "no compute — the coordination floor of this rig",
            "collective_ms_per_step_cal": "collective_ms_per_step_est "
                "minus the same-width null_coordination_ms (floored at "
                "0) — gradient data movement + framework work with the "
                "handshake floor removed",
            "label": "'scheduler-bound' on rungs with n_procs > "
                "host_cores: the host scheduler time-slices collective "
                "partners, so the row is an upper bound on boundary "
                "cost, never a scaling claim (the summary excludes it)",
        },
        "interpretation": (
            "On this rig cross-process collectives ride gloo over "
            "loopback TCP, and with n procs > cores every collective "
            "handshake additionally pays scheduler wake-up latency (the "
            "two sides cannot run simultaneously) — so rungs with "
            "n > cores are UPPER BOUNDS on boundary cost.  The split "
            "shows loader and host-metric overhead are negligible next "
            "to the in-step collective term; on a TPU pod that term is "
            "one fused all-reduce riding ICI inside the compiled step "
            "(COMM_AUDIT: exactly one combined grad all-reduce per step)."
        ),
        "rungs": rungs,
        "calibration": calibration,
    }
    Path(args.out).write_text(json.dumps(out, indent=2) + "\n")
    for r in rungs:
        print(json.dumps(r), flush=True)
    in_gate = [r for r in ok if not r.get("scheduler_bound")]
    print(json.dumps({"summary": "multiproc_scaling",
                      "host_cores": cores,
                      "rungs": [(r["n_procs"],
                                 r.get("contention_corrected_efficiency"))
                                for r in in_gate],
                      "scheduler_bound_rungs": [
                          r["n_procs"] for r in ok
                          if r.get("scheduler_bound")]}), flush=True)
    return 0 if ok and len(ok) == len(rungs) else 1


if __name__ == "__main__":
    sys.exit(main())
