#!/usr/bin/env python3
"""Tunnel-aware measurement shepherd: probe until the TPU revives, then
run the pending on-chip steps in PRIORITY order, returning to the probe
loop whenever the tunnel wedges again.

`hardware_round.py` is the one-shot form: it runs every step back to back
and charges each wedged step its full timeout.  This round showed the
axon tunnel alternates live windows (~minutes) with wedged stretches
(~tens of minutes): a one-shot pass burns its budget confirming the wedge
step by step.  The shepherd inverts that — cheap probes (60 s subprocess
matmul) between steps, and the most-wanted measurements first, so a short
live window yields the highest-value rows before the next wedge:

  1. bench --sections mfu       — the d1024 MFU ladder (VERDICT r3 #2)
  1a. mfu_hunt                  — lever search (batch x remat) + trace
  2. bench --sections decode,fused
  3. bench --sections long      — flash-path long-context rows
  4. flash_sweep GQA            — kernel A/B vs repeated-KV
  5. flash_sweep sliding-window — 32k band kernels
  6. long_context end-to-end (windowed, then dense ladder)
  7. profile summary of the MFU trace (local, no chip)

Each step runs in its own subprocess with a wall-clock bound; results
append to HW_ROUND.json (same schema as hardware_round.py).  A step that
times out is retried up to --max-attempts times, each retry behind a
fresh probe; a step that fails (rc != 0) is recorded and not retried.

Usage: python benchmarks/shepherd.py [--hours 6] [--probe-every 300]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
OUT = REPO / "HW_ROUND.json"
LOG = lambda msg: print(f"[shepherd {time.strftime('%H:%M:%S')}] {msg}",
                        flush=True)

STEPS = [
    ("1_bench_mfu", [sys.executable, "bench.py", "--sections", "mfu"],
     2400, {"TPUDIST_BENCH_PROFILE": "runs/profile_mfu"}),
    ("1a_mfu_hunt", [sys.executable, "benchmarks/mfu_hunt.py"], 3600, {}),
    ("1a2_bench_mfu_scanned",
     [sys.executable, "bench.py", "--sections", "mfu_scanned"], 2000, {}),
    ("1b_bench_decode_fused",
     [sys.executable, "bench.py", "--sections", "decode,fused"], 1500, {}),
    ("1c_bench_long", [sys.executable, "bench.py", "--sections", "long"],
     1800, {}),
    ("2_flash_gqa", [sys.executable, "benchmarks/flash_sweep.py",
                     "--kv-heads", "2", "--grad", "--seq", "2048",
                     "--blocks", "512x512,512x1024"], 1200, {}),
    ("3_flash_window", [sys.executable, "benchmarks/flash_sweep.py",
                        "--seq", "32768", "--window", "1024", "--grad",
                        "--skip-dense", "--blocks", "512x512,512x1024"],
     1800, {}),
    ("4_long_context_window", [sys.executable, "benchmarks/long_context.py",
                               "--seq-lens", "8192", "--seq-shards", "1",
                               "--sliding-window", "1024", "--batch", "4"],
     1200, {}),
    ("5_long_context_dense", [sys.executable, "benchmarks/long_context.py",
                              "--seq-lens", "2048,8192", "--seq-shards", "1",
                              "--batch", "4"], 1200, {}),
    ("6_profile_summary", [sys.executable, "benchmarks/profile_summary.py",
                           "runs/profile_mfu", "--json"], 300, {}),
    # Renamed from 7_autotune: the rc-0 record that name carries in
    # HW_ROUND.json came from the broken (loop-hoisted, non-syncing)
    # timer — a resumed shepherd must re-run the two-point rewrite, not
    # trust that record.
    ("7_autotune_twopoint",
     [sys.executable, "-m", "tpudist.utils.autotune"], 1800, {}),
    # Post-kernel-fix + post-FINAL-autotune reruns (renamed from
    # 8_bench_long_fixedstats / 9_bench_dense_ab / 10_bench_mfu_tuned:
    # those rc-0 records predate the two-point autotune rewrite, so a
    # resumed shepherd must re-measure under the final tuned file, not
    # trust them): the unpadded stats layout (dbf42b2) changes the flash
    # rows' HBM traffic and the tuned 1024x1024 tiles change the
    # attention share of every seq>=1024 row.
    ("8b_bench_long_tuned",
     [sys.executable, "bench.py", "--sections", "long"], 1800, {}),
    ("9b_bench_dense_tuned",
     [sys.executable, "bench.py", "--sections", "dense"], 1800, {}),
    ("10b_bench_mfu_tuned",
     [sys.executable, "bench.py", "--sections", "mfu,mfu_scanned"],
     2400, {}),
]


def probe(timeout_s: float = 60.0) -> bool:
    code = ("import jax, jax.numpy as jnp, numpy as np;"
            "x = jnp.ones((64, 64));"
            "print(float(np.asarray((x @ x).sum())))")
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=timeout_s,
                           capture_output=True, text=True, cwd=REPO)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _load() -> dict:
    try:
        return json.loads(OUT.read_text())
    except Exception:
        return {}


def run_step(name: str, cmd: list, timeout: int, env_extra: dict) -> dict:
    env = {**os.environ, **env_extra}
    t0 = time.time()
    try:
        r = subprocess.run(cmd, timeout=timeout, cwd=REPO,
                           capture_output=True, text=True, env=env)
        rec = {"rc": r.returncode, "seconds": round(time.time() - t0, 1),
               "stdout": r.stdout[-20000:], "stderr": r.stderr[-4000:]}
    except subprocess.TimeoutExpired as e:
        def tail(s):
            if isinstance(s, bytes):
                return s[-4000:].decode("utf-8", "replace")
            return (s or "")[-4000:]
        rec = {"rc": None, "seconds": round(time.time() - t0, 1),
               "error": f"timeout after {timeout}s (tunnel wedged?)",
               "stdout": tail(e.stdout), "stderr": tail(e.stderr)}
    rec["cmd"] = " ".join(cmd)
    return rec


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--hours", type=float, default=6.0,
                   help="total shepherding budget")
    p.add_argument("--probe-every", type=float, default=300.0,
                   help="seconds between probes while wedged")
    p.add_argument("--max-attempts", type=int, default=3)
    args = p.parse_args(argv)

    deadline = time.time() + args.hours * 3600
    attempts: dict[str, int] = {}
    while time.time() < deadline:
        results = _load()
        # next step still owed a run: no record yet, or a TRANSIENT
        # failure with attempts left — a timeout (rc None) or a
        # device-unreachable exit (rc 2, bench.py's _fail_record /
        # hardware_round's probe convention): the tunnel wedging under a
        # step says nothing about the step.  Other nonzero rcs are
        # deterministic failures and terminal.
        pending = []
        for name, cmd, timeout, env in STEPS:
            rec = results.get(name)
            if rec is None or (rec.get("rc") in (None, 2)
                               and attempts.get(name, 0) < args.max_attempts):
                pending.append((name, cmd, timeout, env))
        if not pending:
            LOG("all steps have terminal records — done")
            break
        if not probe():
            LOG(f"tunnel wedged; {len(pending)} steps pending; "
                f"sleeping {args.probe_every:.0f}s")
            time.sleep(args.probe_every)
            continue
        name, cmd, timeout, env = pending[0]
        attempts[name] = attempts.get(name, 0) + 1
        LOG(f"tunnel alive — running {name} "
            f"(attempt {attempts[name]}): {' '.join(cmd)}")
        rec = run_step(name, cmd, timeout, env)
        rec["attempt"] = attempts[name]
        results = _load()  # re-read: bench.py may have updated other keys
        results[name] = rec
        OUT.write_text(json.dumps(results, indent=2) + "\n")
        LOG(f"{name}: {'ok' if rec.get('rc') == 0 else rec.get('error', 'failed')} "
            f"({rec['seconds']}s)")
    final = _load()
    left = [n for n, *_ in STEPS
            if final.get(n) is None or final[n].get("rc") != 0]
    LOG(f"budget exhausted or done; steps without a success: {left}")
    return 0 if not left else 1


if __name__ == "__main__":
    sys.exit(main())
