#!/usr/bin/env python3
"""Loss-parity harness: every entry-point equivalent must train the toy
problem to matching loss (BASELINE.md: "all four entry points reach
matching loss" — the reference's cross-backend eyeball comparison,
SURVEY.md §4.2, as an automated report).

Runs each entry point in-process with a fixed seed and budget, collects
final losses, and reports the spread.  Ideal MSE for the toy task is 0.25
(y = 0.5·ε + x² with ε ~ N(0,1): irreducible variance 0.25²·4 — see
``tpudist/data/toy.py``); "matching" means every entry point lands within
``--tolerance`` of the best.

Usage:  python benchmarks/loss_parity.py [--iters 300] [--tolerance 0.15]
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

ENTRY_POINTS = {
    # name -> (example file, extra argv)
    "demo_dp": ("demo", []),
    "demo_dp_host_metrics": ("demo", ["--backend", "host"]),
    "demo_mpi_bootstrap": ("demo_mpi_bootstrap", []),
    "demo_model_split": ("demo_model_split", []),
    # batch matched to the other entry points (its lightning-shape default
    # of 128 halves the sample budget per iteration — a workload difference,
    # not the numerics difference this harness exists to catch)
    "demo_trainer": ("demo_trainer", ["--batch_size", "256"]),
}


def run_entry(name: str, extra, iters: int, seed: int) -> dict:
    import re
    import contextlib
    import io

    import tpudist.runtime.bootstrap as bs

    spec = importlib.util.spec_from_file_location(
        name, REPO / "examples" / f"{ENTRY_POINTS[name][0]}.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    argv_save = sys.argv
    sys.argv = ["prog", "--dry_run", "--total_iterations", str(iters),
                "--seed", str(seed), "--log_every", str(iters), *extra]
    bs._INITIALIZED_CTX = None
    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf), contextlib.redirect_stderr(io.StringIO()):
            mod.main()
    finally:
        sys.argv = argv_save
    out = buf.getvalue()
    losses = [float(v) for v in re.findall(r"'model_[XY]': ([0-9.eE+-]+)", out)]
    if not losses:
        raise RuntimeError(f"{name}: no final losses in output:\n{out[-500:]}")
    return {"entry_point": name, "final_losses": losses,
            "mean_loss": sum(losses) / len(losses)}


def main(argv=None) -> dict:
    p = argparse.ArgumentParser()
    p.add_argument("--iters", default=300, type=int)
    p.add_argument("--seed", default=0, type=int)
    p.add_argument("--tolerance", default=0.15, type=float,
                   help="max allowed mean-loss gap to the best entry point")
    args = p.parse_args(argv)

    results = []
    for name, (_, extra) in ENTRY_POINTS.items():
        r = run_entry(name, extra, args.iters, args.seed)
        results.append(r)
        print(json.dumps(r))

    best = min(r["mean_loss"] for r in results)
    worst = max(r["mean_loss"] for r in results)
    summary = {
        "summary": "loss_parity",
        "best_mean_loss": round(best, 4),
        "worst_mean_loss": round(worst, 4),
        "spread": round(worst - best, 4),
        "tolerance": args.tolerance,
        "parity": worst - best <= args.tolerance,
        "ideal_mse": 0.25,
    }
    print(json.dumps(summary))
    return summary


if __name__ == "__main__":
    main()
