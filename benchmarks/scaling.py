#!/usr/bin/env python3
"""DP scaling-efficiency harness — establishes the BASELINE.md numbers.

The reference publishes no benchmarks (SURVEY.md §6); the north-star target
set for this repo is samples/sec/chip with ≥80% data-parallel scaling
efficiency as the mesh grows.  This harness measures the toy workload's
throughput at a ladder of data-parallel world sizes on whatever devices are
present and reports efficiency relative to the single-device rung.

On a real pod every rung uses distinct chips and the numbers are true
scaling measurements.  On a CPU host with virtual devices
(``--xla_force_host_platform_device_count=8``) the rungs share one physical
machine — the harness still validates the mechanics end-to-end (and the
tests run it that way), but throughput ratios are not hardware truth; the
report marks which regime produced it.

Output: one JSON line per rung + a summary line, e.g.
  {"world_size": 8, "samples_per_sec": ..., "per_chip": ...,
   "efficiency_vs_1": 0.97, ...}

Usage:
  python benchmarks/scaling.py [--iters 64] [--batch-per-chip 256]
  python benchmarks/scaling.py --world-sizes 1,2,4,8
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def measure_rung(devices, *, batch_per_chip: int, window: int, chunks: int,
                 warmup: int = 3) -> dict:
    """Throughput of the reference workload (two ToyMLPs, Adam, demo.py hot
    loop) data-parallel over ``devices``, scanned-window methodology
    (identical to bench.py so rungs are comparable)."""
    from tpudist.data import make_toy_data
    from tpudist.models import create_toy_model
    from tpudist.runtime.mesh import AXIS_DATA
    from tpudist.train import init_model_states, make_scanned_train_step

    mesh = Mesh(np.asarray(devices), axis_names=(AXIS_DATA,))
    kx, ky = jax.random.split(jax.random.PRNGKey(0))
    mx, px = create_toy_model(kx)
    my, py = create_toy_model(ky)
    models = {"model_X": (mx.apply, px), "model_Y": (my.apply, py)}
    tx = optax.adam(1e-3)
    states = init_model_states(models, tx)
    step = make_scanned_train_step({k: f for k, (f, _) in models.items()}, tx, mesh)

    batch = batch_per_chip * len(devices)
    data = make_toy_data(seed=0)
    repl = NamedSharding(mesh, P())
    x_all = jax.device_put(data.x, repl)
    y_all = jax.device_put(data.y, repl)
    idx = jax.device_put(
        np.random.default_rng(0).integers(
            0, len(data), size=(window, batch)
        ).astype(np.int32),
        repl,
    )

    # Sync via value fetch — block_until_ready can return before remote
    # execution finishes on tunneled platforms (see bench.py).
    for _ in range(warmup):
        states, losses = step(states, x_all, y_all, idx)
    float(losses["model_X"][-1])
    t0 = time.perf_counter()
    for _ in range(chunks):
        states, losses = step(states, x_all, y_all, idx)
    float(losses["model_X"][-1])
    dt = time.perf_counter() - t0

    sps = batch * window * chunks / dt
    return {
        "world_size": len(devices),
        "batch_per_chip": batch_per_chip,
        "samples_per_sec": round(sps, 1),
        "per_chip": round(sps / len(devices), 1),
    }


def main(argv=None) -> list:
    p = argparse.ArgumentParser()
    p.add_argument("--world-sizes", default=None,
                   help="comma list; default: 1,2,4,… up to all devices")
    p.add_argument("--batch-per-chip", default=256, type=int)  # demo.py:145
    p.add_argument("--window", default=32, type=int)
    p.add_argument("--chunks", default=16, type=int)
    args = p.parse_args(argv)

    devices = jax.devices()
    if args.world_sizes:
        sizes = [int(s) for s in args.world_sizes.split(",")]
    else:
        sizes, n = [], 1
        while n <= len(devices):
            sizes.append(n)
            n *= 2
    virtual = devices[0].platform == "cpu"

    results = []
    base_per_chip = None
    for n in sizes:
        if n > len(devices):
            print(f"# skipping world_size {n}: only {len(devices)} devices",
                  file=sys.stderr)
            continue
        r = measure_rung(devices[:n], batch_per_chip=args.batch_per_chip,
                         window=args.window, chunks=args.chunks)
        if base_per_chip is None:
            base_per_chip = r["per_chip"]
        r["efficiency_vs_1"] = round(r["per_chip"] / base_per_chip, 3)
        r["regime"] = "virtual-cpu" if virtual else "hardware"
        results.append(r)
        print(json.dumps(r))

    if results:
        top = results[-1]
        # The ≥80% efficiency target is a statement about hardware scaling;
        # virtual-cpu rungs share one machine's cores, so their ratios only
        # validate mechanics — report no verdict there.
        print(json.dumps({
            "summary": "dp_scaling",
            "max_world_size": top["world_size"],
            "efficiency_vs_1": top["efficiency_vs_1"],
            "target": 0.8,
            "meets_target": (top["efficiency_vs_1"] >= 0.8
                             if top["regime"] == "hardware" else None),
            "regime": top["regime"],
        }))
    return results


if __name__ == "__main__":
    main()
