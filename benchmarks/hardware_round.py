#!/usr/bin/env python3
"""One-shot on-chip measurement round — run the moment the TPU returns.

The axon tunnel can be down for most of a session (BASELINE.md round 2:
an 11+ hour outage stranded a whole round's kernel work unmeasured).
This orchestrator makes a brief hardware window sufficient: it probes the
device, then runs every pending measurement as a SEPARATE subprocess with
its own wall-clock bound (a wedged step is killed and recorded, and the
later steps still get their chance), appending incrementally to
``HW_ROUND.json`` so a mid-round wedge keeps everything measured so far.

Steps (the BASELINE.md "pending on-chip measurements" list + VERDICT r3
items):
  1. bench.py                          — numerics gate + headline + MFU rows
  2. flash_sweep --kv-heads 2 --grad   — GQA-native kernels vs repeated-KV
  3. flash_sweep --seq 32768 --window 1024 --grad  — sliding-window band
  4. long_context --sliding-window 1024            — end-to-end windowed
  5. long_context (dense ring, seq ladder)
  6. profile summary of the MFU row's trace (if captured)

Usage:
  python benchmarks/hardware_round.py            # everything
  python benchmarks/hardware_round.py --only 1,2 # subset
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
OUT = REPO / "HW_ROUND.json"


def _probe(timeout_s: float = 180.0) -> bool:
    """Tiny-matmul reachability probe in a subprocess (a wedged tunnel
    hangs the op; the subprocess is killable, the parent is not)."""
    code = ("import jax, jax.numpy as jnp, numpy as np;"
            "x = jnp.ones((64, 64));"
            "print(float(np.asarray((x @ x).sum())))")
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=timeout_s,
                           capture_output=True, text=True, cwd=REPO)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


STEPS = {
    "1_bench": {
        "cmd": [sys.executable, "bench.py"],
        "timeout": 2400,
        "env": {"TPUDIST_BENCH_PROFILE": "runs/profile_mfu"},
    },
    "2_flash_gqa": {
        "cmd": [sys.executable, "benchmarks/flash_sweep.py",
                "--kv-heads", "2", "--grad", "--seq", "2048",
                "--blocks", "512x512,512x1024"],
        "timeout": 1200,
    },
    "3_flash_window": {
        "cmd": [sys.executable, "benchmarks/flash_sweep.py",
                "--seq", "32768", "--window", "1024", "--grad",
                "--skip-dense", "--blocks", "512x512,512x1024"],
        "timeout": 1800,
    },
    "4_long_context_window": {
        "cmd": [sys.executable, "benchmarks/long_context.py",
                "--seq-lens", "8192", "--seq-shards", "1",
                "--sliding-window", "1024", "--batch", "4"],
        "timeout": 1200,
    },
    "5_long_context_dense": {
        "cmd": [sys.executable, "benchmarks/long_context.py",
                "--seq-lens", "2048,8192", "--seq-shards", "1",
                "--batch", "4"],
        "timeout": 1200,
    },
    "6_profile_summary": {
        "cmd": [sys.executable, "benchmarks/profile_summary.py",
                "runs/profile_mfu", "--json"],
        "timeout": 300,
    },
}


def _run_step(name: str, spec: dict) -> dict:
    env = {**os.environ, **spec.get("env", {})}
    t0 = time.time()
    try:
        r = subprocess.run(spec["cmd"], timeout=spec["timeout"], cwd=REPO,
                           capture_output=True, text=True, env=env)
        return {"rc": r.returncode, "seconds": round(time.time() - t0, 1),
                "stdout": r.stdout[-20000:], "stderr": r.stderr[-4000:]}
    except subprocess.TimeoutExpired as e:
        def _tail(stream):
            if isinstance(stream, bytes):
                return stream[-4000:].decode("utf-8", "replace")
            return (stream or "")[-4000:]

        return {"rc": None, "seconds": round(time.time() - t0, 1),
                "error": f"timeout after {spec['timeout']}s (tunnel wedged?)",
                "stdout": _tail(e.stdout), "stderr": _tail(e.stderr)}


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--only", default=None,
                   help="comma list of step number prefixes (e.g. 1,3)")
    p.add_argument("--skip-probe", action="store_true")
    args = p.parse_args(argv)

    results: dict = {}
    if OUT.exists():
        try:
            results = json.loads(OUT.read_text())
        except Exception:
            results = {}

    if not args.skip_probe and not _probe():
        results["probe"] = {"ok": False, "error": "device unreachable"}
        OUT.write_text(json.dumps(results, indent=2) + "\n")
        print(json.dumps({"probe": "unreachable"}))
        return 2
    results["probe"] = {"ok": True}

    wanted = None
    if args.only:
        wanted = tuple(x.strip() for x in args.only.split(","))
    ran = []
    for name, spec in STEPS.items():
        if wanted and not name.split("_")[0] in wanted:
            continue
        print(f"[hw-round] {name}: {' '.join(spec['cmd'])}", flush=True)
        results[name] = _run_step(name, spec)
        results[name]["cmd"] = " ".join(spec["cmd"])
        ran.append(name)
        # Persist after EVERY step: a later wedge keeps earlier evidence.
        OUT.write_text(json.dumps(results, indent=2) + "\n")
        ok = results[name].get("rc") == 0
        print(f"[hw-round] {name}: "
              f"{'ok' if ok else results[name].get('error', 'failed')} "
              f"({results[name]['seconds']}s)", flush=True)
    # Exit status reflects THIS invocation only (HW_ROUND.json may carry
    # stale rows from a previous partial round).
    bad = [n for n in ran if results[n].get("rc") != 0]
    print(json.dumps({"done": True, "ran": ran, "failed_steps": bad}))
    return 0 if not bad else 1


if __name__ == "__main__":
    sys.exit(main())
