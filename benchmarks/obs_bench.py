#!/usr/bin/env python3
"""Observability-overhead + trace-acceptance bench: the measured (not
assumed) cost of the live observability plane, frozen into
``BENCH_OBS_r{NN}.json``.

Two rungs:

- **obs_twin** — the SAME request set served twice on identical
  engines: once with the live plane armed (metrics feed + per-request
  trace lifelines + a scrape endpoint being polled mid-run), once with
  ``TPUDIST_METRICS=0`` / ``TPUDIST_TRACE=0`` (post-hoc telemetry only,
  yesterday's behavior).  The artifact quotes the wall-TPOT and
  device-busy-per-token deltas — the number the "overhead must be
  measured" acceptance criterion asks for.  On the CPU rig the absolute
  times are interpreter mechanics; the DELTA is the host-side
  record+feed cost, which is exactly the quantity of interest (the
  plane is host-side by construction).

- **trace_chaos** — a disaggregated serve (serial handoff, 2 decode
  workers) with a chaos-killed decode worker
  (``TPUDIST_FAULT=serve_worker_kill``), tracing on.  Validates and
  freezes the acceptance criteria: a single request's trace_id spans
  prefill pool → handoff → decode pool in the exported Perfetto-loadable
  Chrome trace, the chaos-killed lane's replay appears on the survivor
  (two ``req_decode`` segments, different workers), the live ``/metrics``
  scrape parses, and the live TTFT/TPOT percentiles agree with the
  post-hoc aggregator within the quoted sketch-resolution bound
  (``metrics.QUANTILE_REL_ERROR``).

Usage: ``python benchmarks/obs_bench.py [--smoke] [--out PATH]``
(CPU-safe; round_snapshot.py freezes it per round).
"""

from __future__ import annotations

import json
import os
import sys
import time
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

CFG = dict(vocab=32, d_model=32, n_layers=2, n_heads=2, d_ff=64, max_len=64)


def _model(seed: int = 0):
    import jax

    from tpudist.models import create_transformer

    return create_transformer(jax.random.PRNGKey(seed), seq_len=16, **CFG)


def _prompts(n, plen, vocab, seed=0):
    import numpy as np

    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, size=plen).astype(np.int32)
            for _ in range(n)]


def _serve_once(model, prompts, max_new, *, disagg=False, telemetry_dir=None):
    """One serve pass; returns (handles, decode_stats_delta, server)."""
    from tpudist import telemetry
    from tpudist.serve import DisaggServer, InferenceServer, ServeConfig

    if telemetry_dir is not None:
        telemetry.start(telemetry_dir, rank=0, generation=0)
    cfg = ServeConfig(num_slots=4, max_new=max_new, decode_block=8,
                      disagg=disagg, decode_workers=2 if disagg else 1,
                      handoff="serial" if disagg else "device")
    cls = DisaggServer if disagg else InferenceServer
    srv = cls(*model, cfg, install_signal_handler=False).start()
    handles = []
    for i, p in enumerate(prompts):
        handles.append(srv.submit(p, max_new=max_new, tenant=f"t{i % 2}"))
    for h in handles:
        assert h.wait(600), "request timed out"
    return handles, srv


def _tpot_stats(handles):
    vals = sorted(h.tpot_s for h in handles if h.tpot_s is not None)
    if not vals:
        return {"mean": None, "p50": None}
    return {"mean": sum(vals) / len(vals),
            "p50": vals[len(vals) // 2]}


def run_obs_twin(n_requests: int, max_new: int, pairs: int = 3) -> dict:
    """Metrics+trace ON vs OFF on identical traffic and ONE server —
    every wave rides the same compiled programs, so the wave deltas
    isolate the host-side plane cost from XLA compile noise."""
    from tpudist import telemetry
    from tpudist.serve import InferenceServer, ServeConfig
    from tpudist.telemetry import metrics, statusz

    model = _model()
    tdir = Path(os.environ.get("TPUDIST_TELEMETRY_DIR",
                               "runs/telemetry")) / "obs_twin"
    telemetry.start(str(tdir), rank=0, generation=0)
    srv = InferenceServer(
        *model, ServeConfig(num_slots=4, max_new=max_new, decode_block=8),
        install_signal_handler=False).start()
    ep = statusz.ensure_started(port=0)

    def _wave(arm: str, seed: int) -> dict:
        on = arm == "on"
        os.environ["TPUDIST_METRICS"] = "1" if on else "0"
        os.environ["TPUDIST_TRACE"] = "1" if on else "0"
        metrics.arm_from_env()
        d0 = dict(srv.engine.decode_stats())
        handles = []
        scrapes = 0
        for i, p in enumerate(_prompts(n_requests, 6, CFG["vocab"],
                                       seed=seed)):
            handles.append(srv.submit(p, max_new=max_new,
                                      tenant=f"t{i % 2}"))
        for h in handles:
            assert h.wait(600), "request timed out"
        if on and ep is not None:
            # prove the endpoint is live while the server is up; OUTSIDE
            # the measured wave — 3 scrapes inside a ~ms CPU-smoke wave
            # would model a scrape every few ms, 1000x any real cadence
            for _ in range(3):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{ep.port}/metrics", timeout=5).read()
                scrapes += 1
        d1 = srv.engine.decode_stats()
        tokens = sum(len(h.tokens) for h in handles)
        return {
            "tpot": _tpot_stats(handles),
            "tokens": tokens,
            "busy_per_token_s": ((d1["dispatch_s"] - d0["dispatch_s"]
                                  + d1["sync_s"] - d0["sync_s"]) / tokens
                                 if tokens else None),
            "scrapes": scrapes,
        }

    def _median(vals):
        vals = sorted(v for v in vals if v is not None)
        return vals[len(vals) // 2] if vals else None

    # alternating off/on pairs: scheduler noise hits both arms, and each
    # pair is temporally adjacent so the per-pair ratio cancels drift
    offs, ons = [], []
    try:
        _wave("warmup", seed=7)  # pays every XLA compile; discarded
        for i in range(pairs):
            offs.append(_wave("off", seed=i))
            ons.append(_wave("on", seed=i))  # identical prompts per pair
    finally:
        srv.close()
        telemetry.finish(write_report=False)
        statusz.stop()
    tpot_on = _median([w["tpot"]["mean"] for w in ons])
    tpot_off = _median([w["tpot"]["mean"] for w in offs])
    # paired estimator: each pair serves identical prompts back-to-back,
    # so its on/off ratio is immune to the slow load drift that swamps
    # the unpaired medians on a shared CPU rig; the quoted overhead is
    # the MEDIAN pair ratio, with the full spread frozen alongside so
    # the artifact self-documents the rig's noise floor
    ratios = [on["tpot"]["mean"] / off["tpot"]["mean"]
              for on, off in zip(ons, offs)
              if on["tpot"]["mean"] and off["tpot"]["mean"]]
    overhead = (_median(ratios) - 1.0) if ratios else None
    return {
        "rung": "obs_twin",
        "regime": "cpu-smoke",
        "requests": n_requests,
        "max_new": max_new,
        "waves_per_arm": pairs,
        "tokens": sum(w["tokens"] for w in ons),
        "tpot_on_s": tpot_on,
        "tpot_off_s": tpot_off,
        "tpot_overhead_frac": overhead,
        "tpot_on_s_all": [round(w["tpot"]["mean"], 9) for w in ons],
        "tpot_off_s_all": [round(w["tpot"]["mean"], 9) for w in offs],
        "tpot_pair_ratios": [round(r, 6) for r in ratios],
        "busy_per_token_on_s": _median([w["busy_per_token_s"] for w in ons]),
        "busy_per_token_off_s": _median(
            [w["busy_per_token_s"] for w in offs]),
        "mid_run_scrapes": sum(w["scrapes"] for w in ons),
        "note": ("one server, shared compiled programs, warmup wave "
                 "discarded, overhead = median per-pair on/off ratio "
                 "over alternating off/on waves (identical prompts per "
                 "pair) — the on-vs-off DELTA is the host-side "
                 "metrics+trace cost (the plane is host-side by "
                 "construction); CPU-rig absolute TPOT is interpreter "
                 "mechanics and the pair-ratio spread is the rig's "
                 "noise floor"),
    }


def run_trace_chaos(n_requests: int, max_new: int) -> dict:
    """Chaos-killed disagg serve with the plane on: freeze the
    acceptance booleans + live-vs-posthoc percentile agreement."""
    from tpudist import telemetry
    from tpudist.runtime import faults
    from tpudist.telemetry import metrics, statusz, trace
    from tpudist.telemetry.aggregate import aggregate_run, load_records

    model = _model()
    prompts = _prompts(n_requests, 6, CFG["vocab"], seed=1)
    os.environ["TPUDIST_METRICS"] = "1"
    os.environ["TPUDIST_TRACE"] = "1"
    os.environ["TPUDIST_FAULT"] = "serve_worker_kill@call:6,pool:1,worker:0"
    metrics.registry().clear()
    tdir = Path(os.environ.get("TPUDIST_TELEMETRY_DIR",
                               "runs/telemetry")) / "obs_trace_chaos"
    try:
        handles, srv = _serve_once(model, prompts, max_new, disagg=True,
                                   telemetry_dir=str(tdir))
        # live scrape: the endpoint must serve parseable text mid-run
        scrape_ok = False
        ep = statusz.ensure_started(port=0)
        if ep is not None:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{ep.port}/metrics", timeout=5
            ).read().decode()
            scrape_ok = all(
                line.startswith("# TYPE ") or " " in line
                for line in body.strip().splitlines()) and bool(body.strip())
        workers_lost = srv.workers_lost
        lanes_recovered = srv.lanes_recovered
        srv.close()
    finally:
        os.environ.pop("TPUDIST_FAULT", None)
        faults.disarm()
    # live percentiles BEFORE closing the session (scrape-time view)
    reg = metrics.registry()
    live = {}
    for name, metric in (("ttft", "tpudist_ttft_seconds"),
                         ("tpot", "tpudist_tpot_seconds")):
        merged = metrics.Histogram()
        for tenant in ("t0", "t1", "default"):
            merged.merge(reg.histogram(metric, tenant=tenant))
        live[name] = {"p50": merged.quantile(50), "p95": merged.quantile(95),
                      "count": merged.count}
    telemetry.finish(write_report=False)
    statusz.stop()
    # post-hoc: the exact-value aggregator over the same stream
    report = aggregate_run(tdir)
    sv = report["serving"]
    agreement = {}
    within = True
    bound = metrics.QUANTILE_REL_ERROR
    for name in ("ttft", "tpot"):
        for q, field in ((50, "p50_s"), (95, "p95_s")):
            exact = (sv.get(name) or {}).get(field)
            got = live[name][f"p{q}"]
            if not exact:
                continue
            rel = abs(got - exact) / exact
            ok = rel <= bound + 1e-9
            within &= ok
            agreement[f"{name}_p{q}"] = {
                "live_s": round(got, 6), "posthoc_s": round(exact, 6),
                "rel_err": round(rel, 6), "ok": ok}
    # the exported timeline: crossing + replay
    out_trace = trace.export_chrome_trace(tdir)
    doc = json.loads(out_trace.read_text())
    joined = trace.join_traces(load_records(tdir))
    crossed = sum(1 for rs in joined.values()
                  if {"req_prefill", "req_handoff", "req_decode"}
                  <= {r["name"] for r in rs})
    replays = 0
    for rs in joined.values():
        dec = [r for r in rs if r.get("name") == "req_decode"]
        if len(dec) > 1 and len({d.get("worker") for d in dec}) > 1:
            replays += 1
    return {
        "rung": "trace_chaos",
        "regime": "cpu-smoke",
        "requests": n_requests,
        "workers_lost": workers_lost,
        "lanes_recovered": lanes_recovered,
        "lifelines": len(joined),
        "lifelines_crossing_pools": crossed,
        "replays_on_survivor": replays,
        "crossed_pools": crossed > 0,
        "replay_on_survivor": replays > 0,
        "chrome_trace": str(out_trace),
        "chrome_trace_events": len(doc.get("traceEvents", [])),
        "chrome_trace_loadable": bool(doc.get("traceEvents")),
        "scrape_ok": scrape_ok,
        "live_vs_posthoc": agreement,
        "quantile_rel_error_bound": round(bound, 6),
        "live_within_bound": within,
    }


def main(argv=None) -> int:
    import argparse
    import tempfile

    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--smoke", action="store_true",
                   help="CI scale (fewer requests/tokens)")
    p.add_argument("--requests", type=int, default=None)
    p.add_argument("--max-new", type=int, default=None)
    p.add_argument("--pairs", type=int, default=3,
                   help="off/on wave pairs in the twin rung (more pairs "
                        "= tighter overhead median; each pair is cheap)")
    p.add_argument("--out", default=str(REPO / "BENCH_OBS.json"))
    args = p.parse_args(argv)

    n = args.requests or (6 if args.smoke else 16)
    max_new = args.max_new or (10 if args.smoke else 24)
    # hermetic telemetry: this bench owns its streams — and restores
    # every env key it mutates on exit, because the tier-1 bench test
    # calls main() IN-PROCESS (a leaked TPUDIST_TELEMETRY_DIR pointing
    # at this run's temp dir would silently redirect later tests)
    mutated = ("TPUDIST_TELEMETRY_DIR", "TPUDIST_METRICS_PORT",
               "TPUDIST_METRICS", "TPUDIST_TRACE", "TPUDIST_FAULT")
    saved = {k: os.environ.get(k) for k in mutated}
    tmp = tempfile.mkdtemp(prefix="tpudist_obs_bench_")
    os.environ["TPUDIST_TELEMETRY_DIR"] = tmp
    os.environ.pop("TPUDIST_METRICS_PORT", None)  # we bind explicitly

    t0 = time.time()
    try:
        rows = [run_trace_chaos(n, max_new),
                run_obs_twin(n, max_new, pairs=args.pairs)]
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        from tpudist.telemetry import metrics as _metrics

        _metrics.arm_from_env()
    for r in rows:
        r["wall_s"] = round(time.time() - t0, 3)
        print(json.dumps(r))
    out = Path(args.out)
    out.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    print(json.dumps({"wrote": str(out)}))
    chaos = rows[0]
    ok = (chaos["crossed_pools"] and chaos["replay_on_survivor"]
          and chaos["live_within_bound"] and chaos["chrome_trace_loadable"])
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
